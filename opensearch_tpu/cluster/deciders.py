"""Allocation deciders: the veto chain consulted before placing a shard copy.

Re-design of the reference decider stack (the 23 classes under
cluster/routing/allocation/decider/ — SameShardAllocationDecider.java,
FilterAllocationDecider.java, AwarenessAllocationDecider.java,
DiskThresholdDecider.java, ThrottlingAllocationDecider.java,
EnableAllocationDecider.java, ShardsLimitAllocationDecider.java,
ClusterRebalanceAllocationDecider.java,
ConcurrentRebalanceAllocationDecider.java) as pure functions over the
cluster-state payload dict. Each decider returns YES / NO / THROTTLE with a
reason; the chain short-circuits on NO and downgrades to THROTTLE otherwise,
exactly like AllocationDeciders.java's composite.

Inputs come from cluster state, never from live node objects:
  data["settings"]     flat cluster-level dynamic settings
                       (cluster.routing.allocation.*)
  data["node_attrs"]   node_id -> {attr: value} (node.attr.* at join time)
  data["disk_usage"]   node_id -> used fraction 0..1 (reported by monitors;
                       absent nodes are assumed fine, like a missing
                       ClusterInfo in the reference)
  meta["settings"]     index-level settings (index.routing.allocation.*)
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, Optional

YES = "YES"
NO = "NO"
THROTTLE = "THROTTLE"


@dataclass(frozen=True)
class Decision:
    kind: str
    decider: str = ""
    reason: str = ""

    def __bool__(self) -> bool:
        return self.kind == YES


DECISION_YES = Decision(YES)


class AllocationContext:
    """Everything the deciders read, computed once per reroute pass."""

    def __init__(self, data: dict, live: List[str]):
        self.data = data
        self.live = live
        self.settings: Dict = data.get("settings") or {}
        self.node_attrs: Dict[str, Dict] = data.get("node_attrs") or {}
        self.disk_usage: Dict[str, float] = data.get("disk_usage") or {}
        self.indices: Dict[str, dict] = data.get("indices") or {}
        routing = data.get("routing") or {}
        # copies per node and per (node, index); initializing recoveries
        # per node (assigned replicas not yet active = inbound recoveries)
        self.node_copies: Dict[str, int] = {n: 0 for n in live}
        self.node_index_copies: Dict[tuple, int] = {}
        self.node_recoveries: Dict[str, int] = {n: 0 for n in live}
        for index, shards in routing.items():
            for entry in shards:
                for n in ([entry.get("primary")] + entry.get("replicas", [])):
                    if n is None:
                        continue
                    self.node_copies[n] = self.node_copies.get(n, 0) + 1
                    key = (n, index)
                    self.node_index_copies[key] = \
                        self.node_index_copies.get(key, 0) + 1
                active = set(entry.get("active_replicas", []))
                for n in entry.get("replicas", []):
                    if n not in active:
                        self.node_recoveries[n] = \
                            self.node_recoveries.get(n, 0) + 1

    def cluster_setting(self, key: str, default=None):
        return self.settings.get(key, default)

    def index_setting(self, index: str, key: str, default=None):
        """Index-level settings are stored with the `index.` prefix
        STRIPPED by the REST normalizer (indices/service.py
        _normalize_settings); accept both spellings."""
        settings = (self.indices.get(index) or {}).get("settings") or {}
        if key.startswith("index."):
            stripped = key[len("index."):]
            if stripped in settings:
                return settings[stripped]
        return settings.get(key, default)

    def add_copy(self, node: str, index: str, initializing: bool):
        """Account a placement made mid-pass so later decisions see it."""
        self.node_copies[node] = self.node_copies.get(node, 0) + 1
        key = (node, index)
        self.node_index_copies[key] = self.node_index_copies.get(key, 0) + 1
        if initializing:
            self.node_recoveries[node] = self.node_recoveries.get(node, 0) + 1

    def remove_copy(self, node: str, index: str,
                    initializing: bool = False):
        self.node_copies[node] = max(0, self.node_copies.get(node, 0) - 1)
        key = (node, index)
        self.node_index_copies[key] = \
            max(0, self.node_index_copies.get(key, 0) - 1)
        if initializing:
            self.node_recoveries[node] = \
                max(0, self.node_recoveries.get(node, 0) - 1)


# ------------------------------------------------------------------ deciders

def _same_shard(ctx, index, entry, node, is_primary) -> Decision:
    """SameShardAllocationDecider: at most one copy of a shard per node."""
    holders = set(entry.get("replicas", []))
    if entry.get("primary"):
        holders.add(entry["primary"])
    if node in holders:
        return Decision(NO, "same_shard",
                        f"a copy of this shard is already allocated to "
                        f"[{node}]")
    return DECISION_YES


def _filter_decider(ctx: AllocationContext, index: str, entry, node,
                    is_primary) -> Decision:
    """FilterAllocationDecider: cluster + index level include/exclude/require
    on node name or custom attributes (flat keys like
    index.routing.allocation.exclude.zone: "us-east")."""
    attrs = ctx.node_attrs.get(node) or {}

    def node_value(attr: str) -> Optional[str]:
        if attr == "_name":
            return node
        return attrs.get(attr)

    def check(settings: Dict, prefix: str, scope: str) -> Optional[Decision]:
        for (mode, attr), csv in _filter_settings(settings, prefix):
            values = [v.strip() for v in str(csv).split(",") if v.strip()]
            actual = node_value(attr)
            # empty values = the filter was cleared (the reference's
            # "set to empty string to remove" idiom), never a veto-all
            if mode == "require" and values and actual not in values:
                return Decision(NO, "filter",
                                f"node does not match {scope} require "
                                f"filter [{attr}:{csv}]")
            if mode == "include" and values and actual not in values:
                return Decision(NO, "filter",
                                f"node does not match {scope} include "
                                f"filter [{attr}:{csv}]")
            if mode == "exclude" and actual in values:
                return Decision(NO, "filter",
                                f"node matches {scope} exclude filter "
                                f"[{attr}:{csv}]")
        return None

    # NB: Decision.__bool__ is YES-ness — compare to None for "no finding"
    d = check(ctx.settings, "cluster.routing.allocation", "cluster")
    if d is not None:
        return d
    meta_settings = (ctx.indices.get(index) or {}).get("settings") or {}
    # the REST normalizer strips the `index.` prefix; accept both forms
    for prefix in ("index.routing.allocation", "routing.allocation"):
        d = check(meta_settings, prefix, "index")
        if d is not None:
            return d
    return DECISION_YES


def _filter_settings(settings: Dict, prefix: str):
    """Yield ((mode, attr), csv) for every flat filter key under prefix."""
    for full, csv in settings.items():
        if not isinstance(full, str) or not full.startswith(prefix + "."):
            continue
        rest = full[len(prefix) + 1:]
        parts = rest.split(".", 1)
        if len(parts) == 2 and parts[0] in ("require", "include", "exclude"):
            yield (parts[0], parts[1]), csv


def _awareness(ctx: AllocationContext, index: str, entry, node,
               is_primary) -> Decision:
    """AwarenessAllocationDecider: spread copies of a shard across the values
    of each awareness attribute — a node may not hold a copy if doing so puts
    more than ceil(copies / distinct_values) in its zone."""
    attrs_csv = ctx.cluster_setting(
        "cluster.routing.allocation.awareness.attributes", "")
    attributes = [a.strip() for a in str(attrs_csv).split(",") if a.strip()]
    if not attributes:
        return DECISION_YES
    copies = [n for n in ([entry.get("primary")]
                          + entry.get("replicas", [])) if n]
    total_copies = len(copies) + 1          # including the one being placed
    for attr in attributes:
        my_value = (ctx.node_attrs.get(node) or {}).get(attr)
        if my_value is None:
            continue                        # unlabeled nodes aren't gated
        # forced values (awareness.force.zone.values) widen the divisor
        forced = ctx.cluster_setting(
            f"cluster.routing.allocation.awareness.force.{attr}.values", "")
        values = {(ctx.node_attrs.get(n) or {}).get(attr)
                  for n in ctx.live}
        values.discard(None)
        values.add(my_value)
        values |= {v.strip() for v in str(forced).split(",") if v.strip()}
        if not values:
            continue
        per_value = -(-total_copies // len(values))     # ceil
        in_my_value = sum(
            1 for n in copies
            if (ctx.node_attrs.get(n) or {}).get(attr) == my_value)
        if in_my_value + 1 > per_value:
            return Decision(
                NO, "awareness",
                f"too many copies of the shard in [{attr}:{my_value}] "
                f"({in_my_value + 1} > {per_value})")
    return DECISION_YES


def _disk_threshold(ctx: AllocationContext, index: str, entry, node,
                    is_primary) -> Decision:
    """DiskThresholdDecider: refuse new shards above the low watermark
    (high watermark governs can_remain)."""
    if str(ctx.cluster_setting(
            "cluster.routing.allocation.disk.threshold_enabled",
            True)).lower() in ("false", "0"):
        return DECISION_YES
    usage = ctx.disk_usage.get(node)
    if usage is None:
        return DECISION_YES
    low = _pct(ctx.cluster_setting(
        "cluster.routing.allocation.disk.watermark.low", "85%"))
    if usage >= low:
        return Decision(NO, "disk_threshold",
                        f"node [{node}] exceeds the low watermark "
                        f"({usage:.0%} >= {low:.0%})")
    return DECISION_YES


def _throttle(ctx: AllocationContext, index: str, entry, node,
              is_primary) -> Decision:
    """ThrottlingAllocationDecider: bound concurrent inbound recoveries per
    node. Everything that lands with data transfer counts — new replicas AND
    relocation targets (including primary moves, whose target recovers as a
    replica first); only a fresh empty primary (no copies exist anywhere)
    skips the gate."""
    if is_primary and not shard_has_copies(entry):
        return DECISION_YES         # brand-new empty shard: no recovery
    limit = int(ctx.cluster_setting(
        "cluster.routing.allocation.node_concurrent_recoveries", 2))
    if ctx.node_recoveries.get(node, 0) >= limit:
        return Decision(THROTTLE, "throttling",
                        f"node [{node}] already has {limit} concurrent "
                        f"incoming recoveries")
    return DECISION_YES


def shard_has_copies(entry: dict) -> bool:
    return bool(entry.get("primary") or entry.get("replicas"))


def _enable(ctx: AllocationContext, index: str, entry, node,
            is_primary) -> Decision:
    """EnableAllocationDecider (allocation half)."""
    mode = str(ctx.index_setting(
        index, "index.routing.allocation.enable",
        ctx.cluster_setting("cluster.routing.allocation.enable",
                            "all"))).lower()
    if mode == "all":
        return DECISION_YES
    if mode == "none":
        return Decision(NO, "enable", "allocation is disabled")
    if mode == "primaries" and not is_primary:
        return Decision(NO, "enable", "replica allocation is disabled")
    if mode == "new_primaries":
        if not is_primary:
            return Decision(NO, "enable", "replica allocation is disabled")
        if entry.get("primary_term", 0) > 0:
            return Decision(NO, "enable",
                            "only NEW primary allocation is enabled")
    return DECISION_YES


def _shards_limit(ctx: AllocationContext, index: str, entry, node,
                  is_primary) -> Decision:
    """ShardsLimitAllocationDecider: total_shards_per_node at index and
    cluster level."""
    idx_limit = int(ctx.index_setting(
        index, "index.routing.allocation.total_shards_per_node", -1))
    if idx_limit >= 0 and \
            ctx.node_index_copies.get((node, index), 0) >= idx_limit:
        return Decision(NO, "shards_limit",
                        f"node holds {idx_limit} shards of [{index}] "
                        f"already (index.total_shards_per_node)")
    cl_limit = int(ctx.cluster_setting(
        "cluster.routing.allocation.total_shards_per_node", -1))
    if cl_limit >= 0 and ctx.node_copies.get(node, 0) >= cl_limit:
        return Decision(NO, "shards_limit",
                        f"node holds {cl_limit} shards already "
                        f"(cluster.total_shards_per_node)")
    return DECISION_YES


ALLOCATION_DECIDERS = (_enable, _same_shard, _filter_decider, _awareness,
                       _disk_threshold, _shards_limit, _throttle)


def can_allocate(ctx: AllocationContext, index: str, entry: dict,
                 node: str, is_primary: bool) -> Decision:
    """Run the chain; NO short-circuits, THROTTLE is sticky
    (AllocationDeciders.java composite semantics)."""
    throttled: Optional[Decision] = None
    for decider in ALLOCATION_DECIDERS:
        d = decider(ctx, index, entry, node, is_primary)
        if d.kind == NO:
            return d
        if d.kind == THROTTLE and throttled is None:
            throttled = d
    # THROTTLE decisions are falsy (__bool__ is YES-ness): compare to None
    return throttled if throttled is not None else DECISION_YES


def can_remain(ctx: AllocationContext, index: str, entry: dict,
               node: str, is_primary: bool) -> Decision:
    """Whether an already-assigned copy may stay: filters and the HIGH disk
    watermark (DiskThresholdDecider.canRemain)."""
    d = _filter_decider(ctx, index, entry_without(entry, node), node,
                        is_primary)
    if d.kind == NO:
        return d
    if str(ctx.cluster_setting(
            "cluster.routing.allocation.disk.threshold_enabled",
            True)).lower() not in ("false", "0"):
        usage = ctx.disk_usage.get(node)
        if usage is not None:
            high = _pct(ctx.cluster_setting(
                "cluster.routing.allocation.disk.watermark.high", "90%"))
            if usage >= high:
                return Decision(NO, "disk_threshold",
                                f"node [{node}] exceeds the high watermark "
                                f"({usage:.0%} >= {high:.0%})")
    return DECISION_YES


def can_rebalance(ctx: AllocationContext, moving_primary: bool) -> Decision:
    """EnableAllocationDecider (rebalance half) +
    ClusterRebalanceAllocationDecider + ConcurrentRebalanceAllocationDecider.
    Concurrent-move accounting is the caller's (moves_made counter)."""
    mode = str(ctx.cluster_setting("cluster.routing.rebalance.enable",
                                   "all")).lower()
    if mode == "none":
        return Decision(NO, "enable", "rebalancing is disabled")
    if mode == "primaries" and not moving_primary:
        return Decision(NO, "enable", "replica rebalancing is disabled")
    if mode == "replicas" and moving_primary:
        return Decision(NO, "enable", "primary rebalancing is disabled")
    allow = str(ctx.cluster_setting(
        "cluster.routing.allocation.allow_rebalance",
        "indices_all_active")).lower()
    routing = ctx.data.get("routing") or {}
    if allow in ("indices_all_active", "indices_primaries_active"):
        for shards in routing.values():
            for entry in shards:
                if entry.get("primary") is None:
                    return Decision(NO, "cluster_rebalance",
                                    "an unassigned primary exists")
                if allow != "indices_all_active":
                    continue
                # in-flight relocation targets don't count as initializing
                # (the reference decider ignores relocations too, else the
                # first move would veto all others and the concurrent-
                # rebalance budget could never be reached)
                initializing = (set(entry.get("replicas", []))
                                - set(entry.get("active_replicas", [])))
                rel = entry.get("relocating")
                if rel:
                    initializing.discard(rel["to"])
                if initializing:
                    return Decision(NO, "cluster_rebalance",
                                    "a replica is still initializing")
    return DECISION_YES


def entry_without(entry: dict, node: str) -> dict:
    """The shard entry as it would look without `node`'s copy — used by
    can_remain so same_shard-style checks don't see the copy being judged."""
    out = dict(entry)
    if out.get("primary") == node:
        out = {**out, "primary": None}
    out["replicas"] = [n for n in entry.get("replicas", []) if n != node]
    return out


def _pct(value) -> float:
    """'85%' → 0.85; numbers pass through (fractions expected)."""
    s = str(value).strip()
    if s.endswith("%"):
        return float(s[:-1]) / 100.0
    v = float(s)
    return v / 100.0 if v > 1.0 else v
