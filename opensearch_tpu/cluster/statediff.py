"""Cluster-state diff publication: ship deltas, not the world.

Re-design of cluster/Diff.java + ClusterState.diff()/readDiffFrom() and
PublicationTransportHandler: the leader serializes one diff against its
previously-accepted state; a peer whose accepted (term, version) matches
the diff's base applies it, anyone else (fresh joiner, lagging node)
answers "need full" and the leader falls back to a full-state send —
the IncompatibleClusterStateVersionException dance.

The payload diff is two-level: top-level keys of ``ClusterState.data``
(indices, routing, addresses, node_attrs, settings, persistent_tasks,
remote_clusters, ...) diff per-key, and dict-valued entries diff one
level deeper (per index / per node), so touching one index among
thousands ships that index's routing row, not the whole table. The
coordination envelope (term/version/nodes/configs) always travels in
full — it is tiny and must never be reconstructed wrong.
"""

from __future__ import annotations

from typing import Any, Dict, Optional

from opensearch_tpu.cluster.coordination.core import ClusterState

_MISSING = object()


def diff_data(old: Optional[dict], new: Optional[dict]) -> dict:
    """Delta from `old` to `new` payloads. Non-dict payloads (tests drive
    the coordinator with scalar registers) replace wholesale."""
    if not isinstance(new, dict) or not isinstance(old or {}, dict):
        return {"replace": new}
    old = old or {}
    out: Dict[str, Any] = {"set": {}, "del": [], "sub": {}}
    for k in old:
        if k not in new:
            out["del"].append(k)
    for k, v in new.items():
        ov = old.get(k, _MISSING)
        if ov is _MISSING:
            out["set"][k] = v
        elif ov == v:
            continue
        elif isinstance(v, dict) and isinstance(ov, dict):
            sub = {"set": {kk: vv for kk, vv in v.items()
                           if kk not in ov or ov[kk] != vv},
                   "del": [kk for kk in ov if kk not in v]}
            out["sub"][k] = sub
        else:
            out["set"][k] = v
    return out


def apply_data_diff(old: Optional[dict], diff: dict):
    if "replace" in diff:
        return diff["replace"]
    new = dict(old or {})
    for k in diff.get("del", []):
        new.pop(k, None)
    for k, v in diff.get("set", {}).items():
        new[k] = v
    for k, sub in diff.get("sub", {}).items():
        merged = dict(new.get(k) or {})
        for kk in sub.get("del", []):
            merged.pop(kk, None)
        for kk, vv in sub.get("set", {}).items():
            merged[kk] = vv
        new[k] = merged
    return new


def make_state_diff(prev: ClusterState, state: ClusterState) -> dict:
    """The publish payload for peers that hold `prev`."""
    return {
        # full coordination envelope, data stripped (tiny + exact)
        "meta": state.with_(data=None),
        "base_term": prev.term,
        "base_version": prev.version,
        "data": diff_data(prev.data, state.data),
    }


def apply_state_diff(base: ClusterState, diff: dict
                     ) -> Optional[ClusterState]:
    """Reconstruct the published state, or None when `base` is not what
    the diff was computed against (caller answers need_full)."""
    if base is None or base.term != diff["base_term"] \
            or base.version != diff["base_version"]:
        return None
    meta: ClusterState = diff["meta"]
    return meta.with_(data=apply_data_diff(base.data, diff["data"]))
