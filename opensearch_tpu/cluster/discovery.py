"""Seed-hosts discovery: resolve peer addresses and find the cluster.

Re-design of discovery/SeedHostsResolver.java + PeerFinder.java +
FileBasedSeedHostsProvider.java: a seed list names ADDRESSES
("host:port"), not node ids — discovery dials each, handshakes to learn
who answers (HandshakingTransportAddressConnector), and joins through the
first responsive peer. Sources: the `discovery.seed_hosts` setting and the
config-dir `unicast_hosts.txt` file (one host:port per line, # comments).
"""

from __future__ import annotations

import os
import time
from typing import List, Optional, Tuple


def parse_host(entry: str, default_port: int = 9300) -> Tuple[str, int]:
    """host[:port] with IPv6 support: bracketed [::1]:9300 carries a port,
    a bare multi-colon literal (::1, fe80::2) is all host."""
    entry = entry.strip()
    if entry.startswith("["):
        host, _, rest = entry[1:].partition("]")
        if rest.startswith(":"):
            return host, int(rest[1:])
        return host, default_port
    if entry.count(":") == 1:
        host, _, port = entry.partition(":")
        return host, int(port)
    return entry, default_port


def seed_addresses(settings: dict,
                   config_path: Optional[str] = None) -> List[Tuple[str, int]]:
    """Union of the settings list and the file provider, order-preserving."""
    out: List[Tuple[str, int]] = []
    seen = set()

    def add(entry: str):
        try:
            addr = parse_host(entry)
        except ValueError:
            return
        if addr not in seen:
            seen.add(addr)
            out.append(addr)

    hosts = settings.get("discovery.seed_hosts") or []
    if isinstance(hosts, str):
        hosts = [h for h in hosts.split(",") if h.strip()]
    for h in hosts:
        add(h)
    if config_path:
        path = os.path.join(config_path, "unicast_hosts.txt")
        try:
            with open(path) as f:
                for line in f:
                    line = line.split("#", 1)[0].strip()
                    if line:
                        add(line)
        except OSError:
            pass
    return out


def discover_and_join(cluster_node, settings: dict,
                      config_path: Optional[str] = None,
                      timeout: float = 30.0) -> Optional[str]:
    """PeerFinder's probe loop: dial every seed address, handshake, and
    join through the first peer that answers. Returns the seed's node id,
    or None when no peer answered within the timeout (the caller decides
    whether that means bootstrap-a-new-cluster or keep waiting)."""
    seeds = seed_addresses(settings, config_path)
    if not seeds:
        return None
    deadline = time.time() + timeout
    while time.time() < deadline:
        for host, port in seeds:
            node_id = cluster_node.transport.probe_address(
                host, port, timeout=min(5.0, timeout))
            if node_id is not None:
                cluster_node.join((host, port), node_id)
                return node_id
        time.sleep(0.5)
    return None
