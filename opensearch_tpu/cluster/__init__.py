"""Cluster layer: routing, state, allocation, coordination (SURVEY.md §2.1 L3)."""
