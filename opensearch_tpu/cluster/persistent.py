"""Persistent tasks: cluster-state-backed long-running work that survives
node loss.

Re-design of persistent/PersistentTasksClusterService.java +
PersistentTasksNodeService.java + AllocatedPersistentTask: a task lives in
cluster state (``data["persistent_tasks"]``), the leader assigns it to a
live node, the owning node's reconcile loop runs the registered executor,
and when the owner leaves the cluster the leader reassigns the task —
bumping ``allocation_id`` so a zombie executor from the old allocation can
never complete or update the new one (the reference's allocation-id fencing
in PersistentTasksClusterService#completePersistentTask).

State shape:
  data["persistent_tasks"] = {
    task_id: {"name": executor_name, "params": {...},
              "node": node_id | None,     # current assignment
              "allocation_id": int,        # bumped on every (re)assignment
              "status": {...} | None},     # executor-reported progress
  }

Executors register process-wide by name; the executor callable receives
(params, ctx) where ctx is a PersistentTaskContext with is_cancelled(),
update_status(dict) and the owning node. Returning normally completes and
removes the task; raising marks it failed (kept in state with the error so
operators can see it, like the reference's failure status).
"""

from __future__ import annotations

import threading
from typing import Callable, Dict, Optional

# executor registry: name -> fn(params, ctx) -> result
# (PersistentTasksExecutor registry built by plugins in the reference)
PERSISTENT_EXECUTORS: Dict[str, Callable] = {}


def register_executor(name: str, fn: Callable) -> None:
    PERSISTENT_EXECUTORS[name] = fn


class PersistentTaskContext:
    """Handed to a running executor (AllocatedPersistentTask analog)."""

    def __init__(self, cluster_node, task_id: str, allocation_id: int):
        self.cluster_node = cluster_node
        self.task_id = task_id
        self.allocation_id = allocation_id
        self._cancelled = threading.Event()

    def is_cancelled(self) -> bool:
        return self._cancelled.is_set()

    def cancel(self):
        self._cancelled.set()

    def update_status(self, status: dict):
        """Report progress into cluster state (updatePersistentTaskState);
        fenced by allocation id — a stale executor's update is dropped."""
        self.cluster_node._submit_to_leader({
            "kind": "persistent_task_status", "id": self.task_id,
            "allocation_id": self.allocation_id, "status": status})


def assign_tasks(data: dict, live: list) -> None:
    """Leader-side assignment pass, run inside every state fold (mutates
    `data` in place, like the allocator): tasks on dead nodes reassign to
    the live node with the fewest tasks, with an allocation-id bump."""
    tasks: Dict[str, dict] = data.get("persistent_tasks") or {}
    if not tasks:
        return
    live_set = set(live)
    loads = {n: 0 for n in live}
    for t in tasks.values():
        if t.get("node") in loads:
            loads[t["node"]] += 1
    changed = False
    new_tasks = dict(tasks)
    for tid, t in tasks.items():
        if t.get("failed"):
            continue                     # kept for visibility, never re-run
        if t.get("node") in live_set:
            continue
        target: Optional[str] = None
        if loads:
            target = min(sorted(loads), key=lambda n: loads[n])
        nt = dict(t)
        nt["node"] = target
        if target is not None:
            nt["allocation_id"] = t.get("allocation_id", 0) + 1
            loads[target] += 1
        new_tasks = {**new_tasks, tid: nt}
        changed = True
    if changed:
        data["persistent_tasks"] = new_tasks


def fold_update(data: dict, update: dict) -> None:
    """Apply a persistent-task state mutation (the mutate() arms)."""
    kind = update["kind"]
    tasks = dict(data.get("persistent_tasks") or {})
    if kind == "persistent_task_start":
        tid = update["id"]
        if tid in tasks:
            from opensearch_tpu.common.errors import IllegalArgumentError
            raise IllegalArgumentError(
                f"persistent task [{tid}] already exists")
        tasks[tid] = {"name": update["name"],
                      "params": update.get("params") or {},
                      "node": None, "allocation_id": 0, "status": None}
    elif kind == "persistent_task_complete":
        t = tasks.get(update["id"])
        # allocation-id fencing: a reassigned task's old owner can't
        # complete the new allocation
        if t and t.get("allocation_id") == update["allocation_id"]:
            if update.get("error") is not None:
                tasks[update["id"]] = {**t, "failed": True,
                                       "error": update["error"],
                                       "node": None}
            else:
                del tasks[update["id"]]
    elif kind == "persistent_task_status":
        t = tasks.get(update["id"])
        if t and t.get("allocation_id") == update["allocation_id"]:
            tasks[update["id"]] = {**t, "status": update["status"]}
    elif kind == "persistent_task_remove":
        tasks.pop(update["id"], None)
    data["persistent_tasks"] = tasks


class PersistentTaskRunner:
    """Node-side execution (PersistentTasksNodeService): compares the
    state's assignments against locally running allocations, starts new
    ones on the worker pool, cancels ones that moved away or vanished."""

    def __init__(self, cluster_node):
        self.cluster_node = cluster_node
        self._running: Dict[str, PersistentTaskContext] = {}
        self._reported: Dict[str, int] = {}   # task -> alloc failed as
                                              # incapable (dedup)
        self._lock = threading.Lock()

    def reconcile(self, data: dict) -> None:
        tasks: Dict[str, dict] = data.get("persistent_tasks") or {}
        my_id = self.cluster_node.node_id
        with self._lock:
            # cancel allocations we no longer own
            for tid, ctx in list(self._running.items()):
                t = tasks.get(tid)
                if (t is None or t.get("node") != my_id
                        or t.get("allocation_id") != ctx.allocation_id):
                    ctx.cancel()
                    del self._running[tid]
            # prune incapability dedup entries for gone/moved tasks
            for tid in list(self._reported):
                t = tasks.get(tid)
                if t is None or t.get("allocation_id") != self._reported[tid]:
                    del self._reported[tid]
            # start newly assigned ones
            for tid, t in tasks.items():
                if t.get("node") != my_id or t.get("failed"):
                    continue
                if tid in self._running:
                    continue
                fn = PERSISTENT_EXECUTORS.get(t["name"])
                if fn is None:
                    # no executor in this process: fail the task visibly
                    # instead of letting it sit assigned-but-never-running
                    # (the reference only assigns to capable nodes; we
                    # surface incapability as a recorded failure)
                    alloc = t.get("allocation_id", 0)
                    if self._reported.get(tid) != alloc:
                        self._reported[tid] = alloc
                        # fire-and-forget report: NOT the persistent_tasks
                        # pool, whose threads may all be held by lifetime-
                        # long executors (the report would queue forever)
                        self.cluster_node.transport._mgmt_workers.submit(
                            self._report_incapable, tid, alloc, t["name"])
                    continue
                ctx = PersistentTaskContext(self.cluster_node, tid,
                                            t.get("allocation_id", 0))
                self._running[tid] = ctx
                # dedicated pool: task executors live for the task's
                # lifetime, so on the generic pool they starve bulk/CCS
                # fan-out and on the management pool they starve the
                # LEADER_UPDATE deliveries that carry their own
                # cancellation
                self.cluster_node.transport.threadpool.executor(
                    "persistent_tasks").submit(
                    self._run, fn, dict(t.get("params") or {}), ctx)

    def _run(self, fn, params: dict, ctx: PersistentTaskContext):
        error = None
        try:
            fn(params, ctx)
        except Exception as e:           # executor failure -> failed status
            error = str(e) or type(e).__name__
        # report completion, retrying through leader outages — without the
        # retry a completed task whose submit raced a leaderless window
        # would sit in state forever (the owner is alive, so reassignment
        # never triggers). Cancellation (reassignment/removal) ends the
        # loop: the new owner reports instead.
        import time as _time
        while not ctx.is_cancelled():
            try:
                self.cluster_node._submit_to_leader({
                    "kind": "persistent_task_complete", "id": ctx.task_id,
                    "allocation_id": ctx.allocation_id, "error": error})
                return
            except Exception:
                _time.sleep(1.0)

    def _report_incapable(self, tid: str, alloc: int, name: str):
        try:
            self.cluster_node._submit_to_leader({
                "kind": "persistent_task_complete", "id": tid,
                "allocation_id": alloc,
                "error": f"no executor registered for [{name}] on "
                         f"[{self.cluster_node.node_id}]"})
        except Exception:
            self._reported.pop(tid, None)   # retry on the next reconcile

    def running_ids(self):
        with self._lock:
            return dict((tid, c.allocation_id)
                        for tid, c in self._running.items())

    def shutdown(self):
        with self._lock:
            for ctx in self._running.values():
                ctx.cancel()
            self._running.clear()
