"""Doc→shard routing: murmur3 hash partitioning, bit-compatible with the reference.

Contract (cluster/routing/OperationRouting.java:412 generateShardId +
Murmur3HashFunction.java): the routing string is encoded as UTF-16LE code
units, hashed with murmur3_x86_32 seed 0 (Lucene StringHelper), and the shard
id is `floorMod(hash + partitionOffset, routing_num_shards) / routing_factor`
— the two-level scheme that keeps doc placement stable across index shrink.
`routing_partition_size > 1` spreads one routing value over several shards
(partitionOffset = floorMod(murmur3(id), partition_size)).
"""

from __future__ import annotations

from typing import Optional

_MASK = 0xFFFFFFFF


def _rotl32(x: int, r: int) -> int:
    return ((x << r) | (x >> (32 - r))) & _MASK


def murmurhash3_x86_32(data: bytes, seed: int = 0) -> int:
    """Public-domain MurmurHash3 x86_32 (Austin Appleby), signed-int result."""
    c1, c2 = 0xCC9E2D51, 0x1B873593
    h1 = seed & _MASK
    length = len(data)
    rounded = length & ~0x3
    for i in range(0, rounded, 4):
        k1 = int.from_bytes(data[i:i + 4], "little")
        k1 = (k1 * c1) & _MASK
        k1 = _rotl32(k1, 15)
        k1 = (k1 * c2) & _MASK
        h1 ^= k1
        h1 = _rotl32(h1, 13)
        h1 = (h1 * 5 + 0xE6546B64) & _MASK
    k1 = 0
    tail = length & 0x3
    if tail >= 3:
        k1 ^= data[rounded + 2] << 16
    if tail >= 2:
        k1 ^= data[rounded + 1] << 8
    if tail >= 1:
        k1 ^= data[rounded]
        k1 = (k1 * c1) & _MASK
        k1 = _rotl32(k1, 15)
        k1 = (k1 * c2) & _MASK
        h1 ^= k1
    h1 ^= length
    h1 ^= h1 >> 16
    h1 = (h1 * 0x85EBCA6B) & _MASK
    h1 ^= h1 >> 13
    h1 = (h1 * 0xC2B2AE35) & _MASK
    h1 ^= h1 >> 16
    return h1 - (1 << 32) if h1 >= (1 << 31) else h1


def hash_routing(routing: str) -> int:
    """Murmur3HashFunction.hash: UTF-16 code units, little-endian bytes."""
    return murmurhash3_x86_32(routing.encode("utf-16-le"), seed=0)


def generate_shard_id(doc_id: str, num_shards: int,
                      routing: Optional[str] = None,
                      routing_num_shards: Optional[int] = None,
                      routing_partition_size: int = 1) -> int:
    """OperationRouting.generateShardId semantics."""
    if routing_num_shards is None:
        routing_num_shards = num_shards
    routing_factor = routing_num_shards // num_shards
    if routing_partition_size > 1:
        partition_offset = hash_routing(doc_id) % routing_partition_size
        effective = routing if routing is not None else doc_id
    else:
        partition_offset = 0
        effective = routing if routing is not None else doc_id
    h = hash_routing(effective) + partition_offset
    return (h % routing_num_shards) // routing_factor
