"""The consensus safety core: terms, joins, two-phase publish+commit.

Re-design of cluster/coordination/CoordinationState.java — the pure state
machine the reference keeps free of IO so its invariants can be checked in
deterministic simulation. The same separation here: this module has NO
scheduling and NO transport; the Coordinator drives it.

Model (matching the reference's terms):
  - a **term** is an election epoch; StartJoin(term) invites a vote, a Join
    is a vote bound to that term carrying the voter's last-accepted
    (term, version) so stale candidates are rejected by voters comparing
    freshness at vote time;
  - election quorum needs joins from a majority of BOTH the last-committed
    and the last-accepted voting configurations (joint consensus during
    reconfiguration — CoordinationState.isElectionQuorum);
  - publish is two-phase: PublishRequest(state) → quorum of
    PublishResponse → ApplyCommit broadcast (Publication.java semantics).
"""

from __future__ import annotations

from dataclasses import dataclass, field as dc_field, replace
from typing import Any, Dict, FrozenSet, Optional, Set

from opensearch_tpu.common.errors import OpenSearchTpuError


class CoordinationStateRejectedError(OpenSearchTpuError):
    status = 400
    error_type = "coordination_state_rejected_exception"


@dataclass(frozen=True)
class VotingConfiguration:
    """The node ids whose majority decides elections and commits
    (reference: CoordinationMetadata.VotingConfiguration)."""
    node_ids: FrozenSet[str] = frozenset()

    def has_quorum(self, votes: Set[str]) -> bool:
        if not self.node_ids:
            return False
        return len(votes & self.node_ids) * 2 > len(self.node_ids)

    @property
    def is_empty(self) -> bool:
        return not self.node_ids

    @staticmethod
    def of(*ids: str) -> "VotingConfiguration":
        return VotingConfiguration(frozenset(ids))


@dataclass(frozen=True)
class ClusterState:
    """Immutable committed-state snapshot (cluster/ClusterState.java:167).
    `data` carries the application payload (metadata, routing table, ...);
    the coordination layer only reads term/version/configs/nodes."""
    term: int = 0
    version: int = 0
    nodes: FrozenSet[str] = frozenset()
    master_node: Optional[str] = None
    last_committed_config: VotingConfiguration = VotingConfiguration()
    last_accepted_config: VotingConfiguration = VotingConfiguration()
    data: Any = None

    def with_(self, **kw) -> "ClusterState":
        return replace(self, **kw)


@dataclass(frozen=True)
class StartJoinRequest:
    source_node: str     # the candidate soliciting the vote
    term: int


@dataclass(frozen=True)
class Join:
    source_node: str     # the voter
    target_node: str     # the candidate voted for
    term: int
    last_accepted_term: int
    last_accepted_version: int


@dataclass(frozen=True)
class PublishRequest:
    state: ClusterState


@dataclass(frozen=True)
class PublishResponse:
    term: int
    version: int


@dataclass(frozen=True)
class ApplyCommitRequest:
    source_node: str
    term: int
    version: int


class CoordinationState:
    """Per-node consensus state. Persisted pieces (the reference persists
    them via GatewayMetaState): current_term, last_accepted state."""

    def __init__(self, node_id: str, initial_state: ClusterState):
        self.node_id = node_id
        self.current_term = initial_state.term
        self.last_accepted: ClusterState = initial_state
        self.join_votes: Dict[str, Join] = {}
        self.election_won = False
        self.publish_votes: Set[str] = set()
        self.last_published_version = 0
        self.last_published_config = initial_state.last_accepted_config
        self.last_commit_version = initial_state.version

    # ------------------------------------------------------------ accessors

    @property
    def last_accepted_term(self) -> int:
        return self.last_accepted.term

    @property
    def last_accepted_version(self) -> int:
        return self.last_accepted.version

    def is_electable(self) -> bool:
        """A node can only win elections if it's in a voting config
        (reference: locally-elected requirement)."""
        return (self.last_accepted.last_committed_config.is_empty is False)

    # ----------------------------------------------------------- start join

    def handle_start_join(self, request: StartJoinRequest) -> Join:
        """A candidate asked for our vote in a newer term."""
        if request.term <= self.current_term:
            raise CoordinationStateRejectedError(
                f"incoming term {request.term} not greater than current "
                f"term {self.current_term}")
        join = Join(source_node=self.node_id,
                    target_node=request.source_node,
                    term=request.term,
                    last_accepted_term=self.last_accepted_term,
                    last_accepted_version=self.last_accepted_version)
        self.current_term = request.term
        self.join_votes = {}
        self.election_won = False
        self.publish_votes = set()
        self.last_published_version = 0
        return join

    # ----------------------------------------------------------------- join

    def handle_join(self, join: Join) -> bool:
        """Candidate side: count a vote. Returns True when this join wins
        the election."""
        if join.term != self.current_term:
            raise CoordinationStateRejectedError(
                f"incoming term {join.term} does not match current term "
                f"{self.current_term}")
        if join.last_accepted_term > self.last_accepted_term:
            raise CoordinationStateRejectedError(
                "incoming last accepted term "
                f"{join.last_accepted_term} of join higher than current "
                f"last accepted term {self.last_accepted_term}")
        if (join.last_accepted_term == self.last_accepted_term
                and join.last_accepted_version > self.last_accepted_version):
            raise CoordinationStateRejectedError(
                "incoming last accepted version "
                f"{join.last_accepted_version} of join higher than current "
                f"last accepted version {self.last_accepted_version}")
        if self.last_accepted.version == 0 and \
                self.last_accepted.last_accepted_config.is_empty:
            raise CoordinationStateRejectedError(
                "cannot win election before bootstrapping")
        prev_won = self.election_won
        self.join_votes[join.source_node] = join
        self.election_won = self._is_election_quorum(set(self.join_votes))
        return self.election_won and not prev_won

    def _is_election_quorum(self, votes: Set[str]) -> bool:
        return (self.last_accepted.last_committed_config.has_quorum(votes)
                and self.last_accepted.last_accepted_config.has_quorum(votes))

    # -------------------------------------------------------------- publish

    def handle_client_value(self, state: ClusterState) -> PublishRequest:
        """Leader side: start publishing a new state."""
        if not self.election_won:
            raise CoordinationStateRejectedError(
                "only the leader can publish")
        if state.term != self.current_term:
            raise CoordinationStateRejectedError(
                f"incoming term {state.term} does not match current term "
                f"{self.current_term}")
        if self.last_published_version != 0 and \
                state.version != self.last_published_version + 1:
            raise CoordinationStateRejectedError(
                f"incoming version {state.version} does not follow last "
                f"published version {self.last_published_version}")
        if state.version <= self.last_accepted_version and \
                state.term == self.last_accepted_term:
            raise CoordinationStateRejectedError(
                f"incoming version {state.version} not newer than accepted "
                f"{self.last_accepted_version}")
        if state.last_accepted_config != \
                self.last_accepted.last_accepted_config:
            # reconfiguration guards (CoordinationState.handleClientValue):
            # no new reconfiguration while one is still uncommitted, and the
            # election's join votes must form a quorum of the new config.
            if self.last_accepted.last_committed_config != \
                    self.last_accepted.last_accepted_config:
                raise CoordinationStateRejectedError(
                    "only allow reconfiguration while not already "
                    "reconfiguring")
            if not state.last_accepted_config.has_quorum(
                    set(self.join_votes)):
                raise CoordinationStateRejectedError(
                    "only allow reconfiguration if join votes have quorum "
                    "for new config")
        self.last_published_version = state.version
        self.last_published_config = state.last_accepted_config
        self.publish_votes = set()
        return PublishRequest(state)

    def handle_publish_request(self, request: PublishRequest
                               ) -> PublishResponse:
        """Any node: accept a published state (phase 1)."""
        state = request.state
        if state.term != self.current_term:
            raise CoordinationStateRejectedError(
                f"incoming term {state.term} does not match current term "
                f"{self.current_term}")
        if state.term == self.last_accepted_term and \
                state.version <= self.last_accepted_version:
            raise CoordinationStateRejectedError(
                f"incoming version {state.version} lower or equal to "
                f"accepted version {self.last_accepted_version} in term "
                f"{state.term}")
        self.last_accepted = state
        return PublishResponse(term=state.term, version=state.version)

    def handle_publish_response(self, source_node: str,
                                response: PublishResponse
                                ) -> Optional[ApplyCommitRequest]:
        """Leader: collect acks; on quorum return the commit to broadcast."""
        if response.term != self.current_term:
            raise CoordinationStateRejectedError(
                f"incoming term {response.term} does not match current "
                f"term {self.current_term}")
        if response.version != self.last_published_version:
            raise CoordinationStateRejectedError(
                f"incoming version {response.version} does not match "
                f"published version {self.last_published_version}")
        self.publish_votes.add(source_node)
        if self._is_publish_quorum(self.publish_votes):
            return ApplyCommitRequest(source_node=self.node_id,
                                      term=response.term,
                                      version=response.version)
        return None

    def _is_publish_quorum(self, votes: Set[str]) -> bool:
        return (self.last_accepted.last_committed_config.has_quorum(votes)
                and self.last_published_config.has_quorum(votes))

    def handle_commit(self, commit: ApplyCommitRequest) -> ClusterState:
        """Any node: mark the accepted state committed (phase 2)."""
        if commit.term != self.current_term:
            raise CoordinationStateRejectedError(
                f"incoming term {commit.term} does not match current term "
                f"{self.current_term}")
        if commit.term != self.last_accepted_term:
            raise CoordinationStateRejectedError(
                f"incoming term {commit.term} does not match last accepted "
                f"term {self.last_accepted_term}")
        if commit.version != self.last_accepted_version:
            raise CoordinationStateRejectedError(
                f"incoming version {commit.version} does not match last "
                f"accepted version {self.last_accepted_version}")
        # markLastAcceptedStateAsCommitted: a committed state's accepted
        # voting config becomes the committed config, so quorums track the
        # current membership rather than staying frozen at bootstrap.
        if self.last_accepted.last_committed_config != \
                self.last_accepted.last_accepted_config:
            self.last_accepted = self.last_accepted.with_(
                last_committed_config=self.last_accepted.last_accepted_config)
        self.last_commit_version = commit.version
        return self.last_accepted
