"""DisruptableMockTransport: rule-based simulated network for coordination.

Re-design of test/framework disruption machinery
(test/disruption/DisruptableMockTransport.java + NetworkDisruption.java:61):
messages between simulated nodes route through the DeterministicTaskQueue
with per-link rules — blackhole (drop silently), disconnect (fail fast),
delay. Partitions are sets of one-way blocked links; heal() clears them.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional, Set, Tuple

from opensearch_tpu.common.errors import NodeNotConnectedError


class DisruptableMockTransport:
    def __init__(self, task_queue, delivery_delay_ms: int = 10):
        self.task_queue = task_queue
        self.handlers: Dict[str, Dict[str, Callable]] = {}  # node → action → fn
        self.blackholed: Set[Tuple[str, str]] = set()
        self.disconnected: Set[Tuple[str, str]] = set()
        self.delay_ms = delivery_delay_ms
        self.alive: Set[str] = set()

    # ------------------------------------------------------------- registry

    def register_node(self, node_id: str):
        self.handlers.setdefault(node_id, {})
        self.alive.add(node_id)

    def register_handler(self, node_id: str, action: str, handler: Callable):
        self.handlers.setdefault(node_id, {})[action] = handler

    def kill_node(self, node_id: str):
        self.alive.discard(node_id)

    def restart_node(self, node_id: str):
        self.alive.add(node_id)

    # ----------------------------------------------------------- disruption

    def partition(self, side_a: Set[str], side_b: Set[str]):
        for a in side_a:
            for b in side_b:
                self.blackholed.add((a, b))
                self.blackholed.add((b, a))

    def blackhole_link(self, sender: str, target: str):
        self.blackholed.add((sender, target))

    def disconnect_node(self, node_id: str):
        for other in self.handlers:
            if other != node_id:
                self.disconnected.add((node_id, other))
                self.disconnected.add((other, node_id))

    def heal(self):
        self.blackholed.clear()
        self.disconnected.clear()

    # ------------------------------------------------------------- delivery

    def send(self, sender: str, target: str, action: str, payload: Any,
             on_response: Optional[Callable[[Any], None]] = None,
             on_failure: Optional[Callable[[Exception], None]] = None):
        """Asynchronous request/response through virtual time. Responses
        travel back over the same (possibly disrupted) link."""

        def fail(exc):
            if on_failure is not None:
                self.task_queue.schedule_now(
                    lambda: on_failure(exc),
                    f"failure of {action} from {sender} to {target}")

        if (sender, target) in self.blackholed:
            return  # silently dropped; sender's own timeouts must handle it
        if (sender, target) in self.disconnected or target not in self.alive:
            fail(NodeNotConnectedError(f"[{target}] disconnected"))
            return

        def deliver():
            if target not in self.alive:
                fail(NodeNotConnectedError(f"[{target}] disconnected"))
                return
            handler = self.handlers.get(target, {}).get(action)
            if handler is None:
                fail(NodeNotConnectedError(
                    f"no handler for [{action}] on [{target}]"))
                return
            try:
                response = handler(sender, payload)
            except Exception as e:  # handler exception → remote failure
                if (target, sender) not in self.blackholed:
                    fail(e)
                return
            if on_response is not None:
                if (target, sender) in self.blackholed:
                    return  # response lost
                self.task_queue.schedule_delayed(
                    self.delay_ms, lambda: on_response(response),
                    f"response to {action} from {target} to {sender}")

        self.task_queue.schedule_delayed(
            self.delay_ms, deliver, f"delivery of {action} from {sender} "
            f"to {target}")
