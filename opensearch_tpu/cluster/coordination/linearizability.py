"""Linearizability checker for concurrent histories.

Re-design of the reference test-framework's LinearizabilityChecker.java:66
(Wing & Gong / Lowe's algorithm): given a sequential specification and a
concurrent history of [invoke, respond] intervals, search for a linear order
of operations consistent with real-time ordering whose sequential execution
matches every response. Used by the coordination simulation to assert that
cluster-state reads/writes behave like an atomic register (SURVEY.md §5).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Callable, List, Optional, Tuple


class SequentialSpec:
    """A deterministic state machine: initial_state + apply()."""

    def initial_state(self) -> Any:
        raise NotImplementedError

    def apply(self, state: Any, op_input: Any) -> Tuple[Any, Any]:
        """Returns (next_state, expected_output)."""
        raise NotImplementedError


class RegisterSpec(SequentialSpec):
    """Atomic read/write register (the reference's spec for cluster state):
    input ("write", v) → output None; input ("read", None) → output value."""

    def initial_state(self):
        return None

    def apply(self, state, op_input):
        kind, value = op_input
        if kind == "write":
            return value, None
        if kind == "read":
            return state, state
        raise ValueError(f"unknown op {kind}")


@dataclass
class Operation:
    op_input: Any
    output: Any          # None allowed; compared by ==
    invoke_time: int
    response_time: Optional[int]   # None = never returned (crashed client)
    op_id: int = 0


class LinearizabilityChecker:
    def __init__(self, spec: SequentialSpec):
        self.spec = spec

    def is_linearizable(self, history: List[Operation],
                        max_steps: int = 2_000_000) -> bool:
        """Unreturned ops (response_time None) may linearize anywhere after
        their invocation or not at all, per the reference's handling of
        crashed clients."""
        ops = sorted(history, key=lambda o: o.invoke_time)
        for i, op in enumerate(ops):
            op.op_id = i
        n = len(ops)
        steps = [0]

        completed = frozenset(o.op_id for o in ops
                              if o.response_time is not None)

        def search(done: frozenset, state_key, state) -> bool:
            steps[0] += 1
            if steps[0] > max_steps:
                raise RuntimeError("linearizability search budget exceeded")
            if completed <= done:
                return True  # crashed ops may simply never take effect
            # earliest response among not-done ops bounds which ops are
            # candidates: an op can only go next if it was invoked before
            # every not-done op responded (real-time order preserved)
            min_response = min(
                (o.response_time for o in ops
                 if o.op_id not in done and o.response_time is not None),
                default=None)
            for op in ops:
                if op.op_id in done:
                    continue
                if min_response is not None and op.invoke_time > min_response:
                    break  # sorted by invoke_time: no later op qualifies
                next_state, expected = self.spec.apply(state, op.op_input)
                if op.response_time is None:
                    # crashed op: try linearizing it AND try skipping it
                    if search(done | {op.op_id}, None, next_state):
                        return True
                    continue
                if expected == op.output:
                    if search(done | {op.op_id}, None, next_state):
                        return True
            return False

        return search(frozenset(), None, self.spec.initial_state())
