"""DeterministicTaskQueue: seeded virtual-time scheduler for simulation.

Re-design of the reference's test-framework
cluster/coordination/DeterministicTaskQueue.java:61 — the engine under
AbstractCoordinatorTestCase: no threads, no wall clock. Runnable tasks
execute in seeded-random order; deferred tasks fire when virtual time is
advanced to their deadline. Every run with the same seed replays exactly,
which is the race-detection story for the consensus layer (SURVEY.md §5).
"""

from __future__ import annotations

import random
from typing import Callable, List, Optional, Tuple


class DeterministicTaskQueue:
    def __init__(self, seed: int = 0):
        self.random = random.Random(seed)
        self.current_time_ms = 0
        self._runnable: List[Tuple[str, Callable]] = []
        self._deferred: List[Tuple[int, int, str, Callable]] = []
        self._counter = 0

    # ---------------------------------------------------------- scheduling

    def schedule_now(self, fn: Callable, description: str = ""):
        self._runnable.append((description, fn))

    def schedule_at(self, execution_time_ms: int, fn: Callable,
                    description: str = ""):
        if execution_time_ms <= self.current_time_ms:
            self.schedule_now(fn, description)
            return
        self._counter += 1
        self._deferred.append((execution_time_ms, self._counter,
                               description, fn))

    def schedule_delayed(self, delay_ms: int, fn: Callable,
                         description: str = ""):
        self.schedule_at(self.current_time_ms + delay_ms, fn, description)

    # ----------------------------------------------------------- execution

    @property
    def has_runnable_tasks(self) -> bool:
        return bool(self._runnable)

    @property
    def has_deferred_tasks(self) -> bool:
        return bool(self._deferred)

    def run_random_task(self):
        """Run one runnable task, chosen by the seeded random — the
        reordering that shakes out ordering assumptions."""
        i = self.random.randrange(len(self._runnable))
        _, fn = self._runnable.pop(i)
        fn()

    def run_all_runnable_tasks(self):
        while self._runnable:
            self.run_random_task()

    def advance_time(self):
        """Jump virtual time to the next deferred deadline and promote all
        tasks due by then."""
        if not self._deferred:
            return
        self._deferred.sort()
        next_time = self._deferred[0][0]
        self.current_time_ms = next_time
        due = [t for t in self._deferred if t[0] <= next_time]
        self._deferred = [t for t in self._deferred if t[0] > next_time]
        for _, _, description, fn in due:
            self.schedule_now(fn, description)

    def run_until(self, end_time_ms: int):
        """Drive the queue (tasks + time) until virtual `end_time_ms`."""
        while self.current_time_ms < end_time_ms and (
                self._runnable or self._deferred):
            if self._runnable:
                self.run_random_task()
            else:
                self.advance_time()
        self.run_all_runnable_tasks()

    def run_to_quiescence(self, max_time_ms: int = 10 ** 9):
        """Run until no tasks remain (bounded by max_time_ms)."""
        while (self._runnable or self._deferred) and \
                self.current_time_ms < max_time_ms:
            if self._runnable:
                self.run_random_task()
            else:
                self.advance_time()
