from opensearch_tpu.cluster.coordination.core import (
    ClusterState, CoordinationState, VotingConfiguration)
from opensearch_tpu.cluster.coordination.coordinator import Coordinator, Mode
from opensearch_tpu.cluster.coordination.deterministic import (
    DeterministicTaskQueue)
from opensearch_tpu.cluster.coordination.mock_transport import (
    DisruptableMockTransport)

__all__ = ["ClusterState", "CoordinationState", "VotingConfiguration",
           "Coordinator", "Mode", "DeterministicTaskQueue",
           "DisruptableMockTransport"]
