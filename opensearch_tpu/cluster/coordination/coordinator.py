"""Coordinator: the CANDIDATE/LEADER/FOLLOWER election + publication driver.

Re-design of cluster/coordination/Coordinator.java:119 over the safety core
in core.py. All IO goes through a transport with `send(sender, target,
action, payload, on_response, on_failure)` and all timing through a
scheduler with `schedule_delayed(ms, fn, desc)` + `current_time_ms` —
satisfied by the deterministic harness in tests and by a real clock/socket
pair in production.

Mechanisms ported (reference anchors):
  - randomized election scheduling with linear backoff
    (ElectionSchedulerFactory);
  - pre-vote round before term bump (PreVoteCollector) so partitioned
    nodes don't inflate terms;
  - join accumulation → become leader on quorum (JoinHelper,
    Coordinator.handleJoinRequest:574);
  - two-phase publish (Publication.java / Coordinator.publish:1245) with
    the node-join fast path (leader publishes state incl. new node);
  - leader-side FollowersChecker + follower-side LeaderChecker with
    3-strike removal (FollowersChecker.java / LeaderChecker.java);
  - auto-reconfiguration of the voting config toward an odd-sized majority
    of live master-eligible nodes (Reconfigurator.java).
"""

from __future__ import annotations

import enum
from typing import Any, Callable, Dict, List, Optional, Set, Tuple

from opensearch_tpu.cluster.coordination.core import (
    ApplyCommitRequest, ClusterState, CoordinationState,
    CoordinationStateRejectedError, Join, PublishRequest, PublishResponse,
    StartJoinRequest, VotingConfiguration)

# action names (reference: Coordinator's registered transport actions)
JOIN_ACTION = "internal:cluster/coordination/join"
PUBLISH_ACTION = "internal:cluster/coordination/publish_state"
COMMIT_ACTION = "internal:cluster/coordination/commit_state"
PRE_VOTE_ACTION = "internal:cluster/request_pre_vote"
FOLLOWER_CHECK_ACTION = "internal:coordination/fault_detection/follower_check"
LEADER_CHECK_ACTION = "internal:coordination/fault_detection/leader_check"

ELECTION_INITIAL_TIMEOUT_MS = 100      # cluster.election.initial_timeout
ELECTION_BACKOFF_MS = 100              # cluster.election.back_off_time
ELECTION_MAX_TIMEOUT_MS = 10_000       # cluster.election.max_timeout
FOLLOWER_CHECK_INTERVAL_MS = 1_000     # follower_check.interval
LEADER_CHECK_INTERVAL_MS = 1_000       # leader_check.interval
CHECK_RETRY_COUNT = 3                  # *_check.retry_count


class Mode(enum.Enum):
    CANDIDATE = "CANDIDATE"
    LEADER = "LEADER"
    FOLLOWER = "FOLLOWER"


class NotLeaderAbort(Exception):
    """A queued/in-flight state update was aborted because this node lost
    (or never committed) leadership — the caller should retry against the
    current leader (reference: FailedToCommitClusterStateException /
    NotMasterException, both retryable)."""

def _safe_notify(listener, outcome) -> None:
    """Invoke an update listener, never letting its exception escape the
    coordinator's state machine."""
    if listener is not None:
        try:
            listener(outcome)
        except Exception:
            pass


class Coordinator:
    def __init__(self, node_id: str, transport, scheduler,
                 initial_state: ClusterState,
                 on_state_applied: Optional[Callable[[ClusterState], None]]
                 = None,
                 health: Optional[Callable[[], bool]] = None):
        self.node_id = node_id
        # NodeHealthService analog (monitor.FsHealthService feeds this):
        # an unhealthy node fails its follower checks (→ 3-strike removal
        # by the leader), refuses pre-votes, and never starts elections —
        # reference: FsHealthService.java:74 → Coordinator's StatusInfo
        self.health = health or (lambda: True)
        self.transport = transport
        self.scheduler = scheduler
        self.coord_state = CoordinationState(node_id, initial_state)
        self.mode = Mode.CANDIDATE
        self.leader: Optional[str] = None
        self.applied_state: ClusterState = initial_state
        self.on_state_applied = on_state_applied
        self.known_peers: Set[str] = set(initial_state.nodes) | {node_id}
        self._election_round = 0
        self._election_epoch = 0           # invalidates scheduled elections
        self._check_failures: Dict[str, int] = {}
        self._leader_check_failures = 0
        self._stopped = False
        self._publish_in_flight = False
        # diff-vs-full publication accounting (PublishClusterStateStats)
        self.publish_stats = {"diff": 0, "full": 0}
        # (update_fn, listener) pairs; listener(None) on successful fold
        # into a publication, listener(exc) if the update itself raised —
        # MasterService's per-task onFailure isolation: one poison task
        # must never wedge the queue
        self._pending_values: List[Tuple[
            Callable[[ClusterState], ClusterState],
            Optional[Callable[[Optional[Exception]], None]]]] = []
        # listeners of the publication currently in flight, acked on
        # commit quorum / failed on publication failure or depose
        self._inflight_listeners: List[
            Optional[Callable[[Optional[Exception]], None]]] = []
        self._pending_joins: Set[str] = set()

        t = transport
        t.register_handler(node_id, PRE_VOTE_ACTION, self._on_pre_vote)
        t.register_handler(node_id, JOIN_ACTION, self._on_join)
        t.register_handler(node_id, PUBLISH_ACTION, self._on_publish)
        t.register_handler(node_id, COMMIT_ACTION, self._on_commit)
        t.register_handler(node_id, FOLLOWER_CHECK_ACTION,
                           self._on_follower_check)
        t.register_handler(node_id, LEADER_CHECK_ACTION,
                           self._on_leader_check)

    # ---------------------------------------------------------------- start

    def start(self):
        self._become_candidate("started")

    def stop(self):
        self._stopped = True

    # ------------------------------------------------------- mode switches

    def _become_candidate(self, reason: str):
        self.mode = Mode.CANDIDATE
        self.leader = None
        # any in-flight publication is dead once deposed: clear the slot so
        # a later re-election can publish again (the timeout timer is bound
        # to a version and would no longer clear it for us)
        self._publish_in_flight = False
        self._fail_pending_updates(f"leader stepped down: {reason}")
        self._leader_check_failures = 0
        self._election_epoch += 1
        self._schedule_election()

    def _become_leader(self):
        self.mode = Mode.LEADER
        self.leader = self.node_id
        self._publish_in_flight = False
        self._election_epoch += 1
        self._check_failures = {}
        self._schedule_follower_checks()
        # first publication of the new term: pick up joined nodes + reconfig
        self._publish_next()

    def _become_follower(self, leader: str):
        if self.mode == Mode.FOLLOWER and self.leader == leader:
            return
        self.mode = Mode.FOLLOWER
        self.leader = leader
        self._publish_in_flight = False
        self._fail_pending_updates(f"following [{leader}]")
        self._leader_check_failures = 0
        self._election_epoch += 1
        self._schedule_leader_check()

    def _fail_pending_updates(self, reason: str):
        """On losing leadership, every queued or in-flight client update is
        failed to its listener (MasterService onNoLongerMaster): listeners
        therefore fire exactly once, and callers retry against the new
        leader instead of hanging or double-submitting."""
        pending, self._pending_values = self._pending_values, []
        inflight, self._inflight_listeners = self._inflight_listeners, []
        for listener in ([l for _, l in pending] + inflight):
            _safe_notify(listener, NotLeaderAbort(reason))

    # ------------------------------------------------------------ elections

    def _schedule_election(self):
        if self._stopped:
            return
        epoch = self._election_epoch
        self._election_round += 1
        max_delay = min(ELECTION_INITIAL_TIMEOUT_MS
                        + ELECTION_BACKOFF_MS * self._election_round,
                        ELECTION_MAX_TIMEOUT_MS)
        delay = self.scheduler.random.randrange(max_delay) + 1 \
            if hasattr(self.scheduler, "random") else max_delay // 2

        def maybe_run():
            if self._stopped or self.mode != Mode.CANDIDATE \
                    or epoch != self._election_epoch:
                return
            self._start_pre_vote()
            self._schedule_election()  # retry with backoff until leader known

        self.scheduler.schedule_delayed(delay, maybe_run,
                                        f"election on {self.node_id}")

    def _start_pre_vote(self):
        """PreVoteCollector: ask peers whether they'd vote for us in
        term+1 before actually disrupting the term."""
        votes: Set[str] = set()
        responded: Set[str] = set()
        proposed_term = self.coord_state.current_term + 1
        me = self.node_id

        def on_response(peer):
            def handle(resp):
                if self.mode != Mode.CANDIDATE:
                    return
                responded.add(peer)
                if resp.get("leader") and resp["leader"] != me:
                    if not self.health():
                        return  # rejoining while unhealthy would flap
                    # a healthy leader exists: rejoin it instead of electing
                    self.join_cluster(resp["leader"])
                    return
                if resp.get("would_vote"):
                    votes.add(peer)
                config = self.coord_state.last_accepted.last_committed_config
                if config.has_quorum(votes | {me}) and \
                        self.mode == Mode.CANDIDATE:
                    self._start_election(proposed_term)
            return handle

        payload = {"term": proposed_term,
                   "last_accepted_term": self.coord_state.last_accepted_term,
                   "last_accepted_version":
                       self.coord_state.last_accepted_version}
        config = self.coord_state.last_accepted.last_committed_config
        if config.has_quorum({me}):
            self._start_election(proposed_term)
            return
        for peer in self.known_peers - {me}:
            self.transport.send(me, peer, PRE_VOTE_ACTION, payload,
                                on_response(peer), lambda e: None)

    def _on_pre_vote(self, sender: str, payload: dict):
        self.known_peers.add(sender)
        would_vote = (
            self.health()
            and payload["term"] > self.coord_state.current_term
            and (payload["last_accepted_term"],
                 payload["last_accepted_version"])
            >= (self.coord_state.last_accepted_term,
                self.coord_state.last_accepted_version)
            # a live leader vetoes pre-votes so healthy clusters stay stable
            and not (self.mode == Mode.LEADER
                     or (self.mode == Mode.FOLLOWER
                         and self._leader_check_failures == 0
                         and self.leader is not None)))
        healthy_leader = self.leader if (
            self.mode == Mode.LEADER
            or (self.mode == Mode.FOLLOWER
                and self._leader_check_failures == 0)) else None
        return {"would_vote": would_vote, "leader": healthy_leader}

    def _start_election(self, term: int):
        if not self.health():
            return      # an unhealthy node must not stand for leader
        """Send StartJoin(term) to every peer incl. ourselves — votes come
        back as joins (Coordinator.startElection:498)."""
        if term <= self.coord_state.current_term:
            term = self.coord_state.current_term + 1
        start = StartJoinRequest(source_node=self.node_id, term=term)
        for peer in sorted(self.known_peers):
            if peer == self.node_id:
                self._request_join_from_self(start)
            else:
                # the voter computes its Join against the StartJoin and
                # returns it as the RPC response (JoinHelper's round trip)
                self.transport.send(
                    self.node_id, peer, JOIN_ACTION,
                    {"start_join": (start.source_node, start.term)},
                    self._on_join_response, lambda e: None)

    def _request_join_from_self(self, start: StartJoinRequest):
        try:
            join = self.coord_state.handle_start_join(start)
            self._handle_incoming_join(join)
        except CoordinationStateRejectedError:
            pass

    def _on_join(self, sender: str, payload: dict):
        """A candidate solicits our vote (or a node asks to join the
        cluster when payload has no start_join)."""
        self.known_peers.add(sender)
        if "start_join" in payload:
            source, term = payload["start_join"]
            start = StartJoinRequest(source_node=source, term=term)
            join = self.coord_state.handle_start_join(start)
            if self.mode != Mode.CANDIDATE and source != self.leader:
                # accepting a newer term deposes us
                self._become_candidate(f"start_join from {source}")
            return {"join": (join.source_node, join.target_node, join.term,
                             join.last_accepted_term,
                             join.last_accepted_version)}
        if "join" in payload:
            # a joiner's vote for the current term (JoinHelper: a join
            # request carries an optional Join when the sender adopted our
            # term) — recorded so reconfiguration quorums can include it.
            source, target, term, la_term, la_version = payload["join"]
            self._handle_incoming_join(Join(source, target, term, la_term,
                                            la_version))
            if self.mode == Mode.LEADER:
                self._publish_next()
                return {"accepted": True}
            return {"accepted": False, "leader": self.leader}
        # plain join request: node wants into the cluster (leader side)
        if self.mode == Mode.LEADER:
            self._pending_joins.add(sender)
            self._publish_next()
            # return our term so the joiner can send a proper join vote
            return {"accepted": True, "term": self.coord_state.current_term}
        return {"accepted": False, "leader": self.leader}

    def _on_join_response(self, resp):
        if not resp or "join" not in resp:
            return
        source, target, term, la_term, la_version = resp["join"]
        self._handle_incoming_join(Join(source, target, term, la_term,
                                        la_version))

    def _handle_incoming_join(self, join: Join):
        if join.target_node != self.node_id:
            return
        try:
            won = self.coord_state.handle_join(join)
        except CoordinationStateRejectedError:
            return
        self._pending_joins.add(join.source_node)
        if won and self.mode == Mode.CANDIDATE:
            self._become_leader()

    # ---------------------------------------------------------- publication

    def submit_state_update(self, update: Callable[[ClusterState],
                                                   ClusterState],
                            listener: Optional[Callable[
                                [Optional[Exception]], None]] = None,
                            ) -> bool:
        """MasterService.submitStateUpdateTask analog: leader-only, updates
        are queued and published in order (single-threaded batch).
        `listener` is invoked once with None when the update folds into a
        publication, or with the exception if the update raised."""
        if self.mode != Mode.LEADER:
            return False
        self._pending_values.append((update, listener))
        self._publish_next()
        return True

    def _publish_next(self):
        if self.mode != Mode.LEADER or self._publish_in_flight \
                or self._stopped:
            return
        base = self.coord_state.last_accepted
        # fold in queued client updates + joined nodes + reconfiguration
        new_nodes = frozenset(set(base.nodes) | self._pending_joins
                              | {self.node_id})
        data = base.data
        taken_values = self._pending_values
        taken_joins = self._pending_joins
        surviving: List = []
        for update, listener in taken_values:
            # isolate each task: a raising update notifies its listener and
            # is dropped; the rest of the batch — and the leader — proceed
            # (MasterService catches per-task exceptions the same way)
            try:
                tmp = update(base.with_(nodes=new_nodes, data=data))
            except Exception as e:
                _safe_notify(listener, e)
                continue
            data = tmp.data
            new_nodes = tmp.nodes
            surviving.append((update, listener))
        self._pending_values = []
        self._pending_joins = set()

        def ack_applied():
            for _, listener in surviving:
                _safe_notify(listener, None)
        if base.last_accepted_config != base.last_committed_config:
            # a reconfiguration is still uncommitted: don't start another
            # (handleClientValue would reject it) — republish same config
            new_config = base.last_accepted_config
        else:
            new_config = self._reconfigure(new_nodes)
        if (new_nodes == base.nodes and data is base.data
                and new_config == base.last_accepted_config
                and base.term == self.coord_state.current_term
                and base.master_node == self.node_id):
            ack_applied()   # no-op updates still complete successfully
            return  # nothing to publish
        state = base.with_(
            term=self.coord_state.current_term,
            version=max(base.version,
                        self.coord_state.last_published_version) + 1,
            nodes=new_nodes,
            master_node=self.node_id,
            last_accepted_config=new_config,
            data=data)
        try:
            request = self.coord_state.handle_client_value(state)
        except CoordinationStateRejectedError:
            # keep the surviving client updates and joins for the next
            # publish round instead of silently dropping them (the raising
            # ones were already failed to their listeners)
            self._pending_values = surviving + self._pending_values
            self._pending_joins |= taken_joins
            return
        self._publish_in_flight = True
        # listeners ack at COMMIT time (_finish_publication), not here — a
        # publication that fails its quorum must fail its listeners, or a
        # client could hold acknowledged=true for a change that was lost
        self._inflight_listeners = [l for _, l in surviving]
        self._publish(request)

    def _reconfigure(self, nodes: frozenset) -> VotingConfiguration:
        """Reconfigurator: voting config = live nodes with a join vote
        (Coordinator.improveConfiguration filters by hasJoinVoteFrom) plus
        live members of the current config (stability: a node that voted for
        a losing candidate this term keeps its seat), trimmed to an odd
        count. The join-quorum guard in handle_client_value needs only a
        majority of the result to have voted, which retention preserves."""
        voted = set(self.coord_state.join_votes) | {self.node_id}
        current = self.coord_state.last_accepted.last_accepted_config.node_ids
        members = sorted(n for n in nodes if n in voted or n in current)
        if not members:
            members = [self.node_id]
        if len(members) % 2 == 0 and len(members) > 1:
            # drop one to keep quorum odd: prefer a non-voted member, never
            # the leader
            droppable = ([n for n in members
                          if n not in voted and n != self.node_id]
                         or [n for n in members if n != self.node_id])
            members.remove(droppable[-1])
        config = VotingConfiguration(frozenset(members))
        if not config.has_quorum(voted):
            # would fail handle_client_value's join-quorum guard: keep the
            # existing configuration until more joins arrive
            return self.coord_state.last_accepted.last_accepted_config
        return config

    def _publish(self, request: PublishRequest):
        """Publication.java: fan the state to every node; once a commit
        quorum of publish acks arrives, send ApplyCommit to each node that
        has acked (never to one that hasn't — commit must not overtake the
        publish on a node that hasn't accepted the state yet); late acks
        get their commit on arrival."""
        state = request.state
        reached_commit: List[Optional[ApplyCommitRequest]] = [None]

        def on_response(peer):
            def handle(resp):
                if resp is None or self.mode != Mode.LEADER:
                    return
                if resp.get("join"):
                    # the peer adopted our term with this publish and piggy-
                    # backed its join vote (PublishWithJoinResponse)
                    self._handle_incoming_join(Join(*resp["join"]))
                if reached_commit[0] is not None:
                    self._send_commit(peer, reached_commit[0])
                    return
                try:
                    commit = self.coord_state.handle_publish_response(
                        peer, PublishResponse(term=resp["term"],
                                              version=resp["version"]))
                except CoordinationStateRejectedError:
                    return
                if commit is not None:
                    reached_commit[0] = commit
                    acked = set(self.coord_state.publish_votes)
                    self._finish_publication(commit, state, acked)
            return handle

        # diff publication (PublicationTransportHandler): peers holding the
        # previous accepted state get a delta; anyone else answers
        # need_full and we resend the complete state
        from opensearch_tpu.cluster.statediff import make_state_diff
        full_payload = {"state": state}
        prev = self.coord_state.last_accepted
        diff_ok = prev is not None and prev.version > 0
        diff_box: list = [None]     # built lazily: a single-node cluster
                                    # (or all-joiner fan-out) never pays
                                    # the O(state) diff walk

        def diff_payload():
            if diff_box[0] is None:
                diff_box[0] = {"diff": make_state_diff(prev, state)}
            return diff_box[0]

        def wrap(peer):
            inner = on_response(peer)

            def handle(resp):
                if resp and resp.get("need_full"):
                    self.publish_stats["full"] += 1
                    self.transport.send(self.node_id, peer, PUBLISH_ACTION,
                                        full_payload, inner,
                                        lambda e: None)
                    return
                inner(resp)
            return handle

        for peer in sorted(state.nodes):
            if peer == self.node_id:
                try:
                    resp = self.coord_state.handle_publish_request(request)
                    on_response(peer)({"term": resp.term,
                                       "version": resp.version})
                except CoordinationStateRejectedError:
                    pass
            elif diff_ok and peer in prev.nodes:
                # peers absent from the previous state (fresh joiners) hold
                # no base — a diff would just burn a need_full round trip
                self.publish_stats["diff"] += 1
                self.transport.send(self.node_id, peer, PUBLISH_ACTION,
                                    diff_payload(), wrap(peer),
                                    lambda e: None)
            else:
                self.publish_stats["full"] += 1
                self.transport.send(self.node_id, peer, PUBLISH_ACTION,
                                    full_payload, on_response(peer),
                                    lambda e: None)
        self.scheduler.schedule_delayed(
            30_000, lambda: self._publish_timeout(state.version),
            "publish timeout")

    def _publish_timeout(self, published_version: int):
        """Publication.java onTimeout: a publication that cannot reach a
        commit quorum within the timeout deposes the leader — this is how a
        minority-side leader stands down after a partition. The timer is
        bound to the publication that armed it (by version) so a stale timer
        from an earlier, long-committed publication cannot depose a healthy
        leader while a later publication is briefly in flight."""
        if self._publish_in_flight and \
                self.coord_state.last_published_version == published_version:
            self._publish_in_flight = False
            if self.mode == Mode.LEADER:
                # _become_candidate fails the in-flight listeners too
                self._become_candidate("publication failed to commit")

    def _send_commit(self, peer: str, commit: ApplyCommitRequest):
        if peer == self.node_id:
            self._apply_commit(commit)
        else:
            self.transport.send(
                self.node_id, peer, COMMIT_ACTION,
                {"term": commit.term, "version": commit.version},
                None, lambda e: None)

    def _finish_publication(self, commit: ApplyCommitRequest,
                            state: ClusterState, acked_peers: Set[str]):
        """Commit quorum reached: deliver ApplyCommit to the peers that
        acked the publish and release the publication slot."""
        if not self._publish_in_flight:
            return  # already committed this publication
        self._publish_in_flight = False
        listeners, self._inflight_listeners = self._inflight_listeners, []
        for listener in listeners:
            _safe_notify(listener, None)
        for peer in sorted(acked_peers):
            self._send_commit(peer, commit)
        # more queued work?
        if self._pending_values or self._pending_joins:
            self.scheduler.schedule_now(self._publish_next,
                                        "publish queued updates")

    def _on_publish(self, sender: str, payload: dict):
        if "state" in payload:
            state: ClusterState = payload["state"]
        else:
            # diff publication: reconstruct against our accepted state, or
            # ask for the full state when the base doesn't match (fresh
            # joiner / lagging node — IncompatibleClusterStateVersion)
            from opensearch_tpu.cluster.statediff import apply_state_diff
            state = apply_state_diff(self.coord_state.last_accepted,
                                     payload["diff"])
            if state is None:
                return {"need_full": True}
        self.known_peers |= set(state.nodes)
        join = None
        if state.term > self.coord_state.current_term:
            # accept the newer term implicitly (like handling a StartJoin)
            # and hand the new leader our join vote with the response
            join = self.coord_state.handle_start_join(
                StartJoinRequest(source_node=sender, term=state.term))
        resp = self.coord_state.handle_publish_request(
            PublishRequest(state))
        if sender != self.node_id:
            self._become_follower(sender)
        out = {"term": resp.term, "version": resp.version}
        if join is not None:
            out["join"] = (join.source_node, join.target_node, join.term,
                           join.last_accepted_term,
                           join.last_accepted_version)
        return out

    def _on_commit(self, sender: str, payload: dict):
        commit = ApplyCommitRequest(source_node=sender,
                                    term=payload["term"],
                                    version=payload["version"])
        self._apply_commit(commit)
        return {"ok": True}

    def _apply_commit(self, commit: ApplyCommitRequest):
        try:
            state = self.coord_state.handle_commit(commit)
        except CoordinationStateRejectedError:
            return
        self.applied_state = state
        self.known_peers |= set(state.nodes)
        if self.on_state_applied is not None:
            self.on_state_applied(state)

    # ------------------------------------------------------ fault detection

    CHECK_TIMEOUT_MS = 10_000   # follower_check.timeout / leader_check.timeout

    def _send_with_timeout(self, target: str, action: str, payload,
                           on_ok, on_fail):
        """Fault-detection RPCs fail on timeout too (blackholed links drop
        messages silently — the reference's checks have explicit timeouts)."""
        settled = [False]

        def ok(resp):
            if not settled[0]:
                settled[0] = True
                on_ok(resp)

        def fail(exc):
            if not settled[0]:
                settled[0] = True
                on_fail(exc)

        self.transport.send(self.node_id, target, action, payload, ok, fail)
        self.scheduler.schedule_delayed(
            self.CHECK_TIMEOUT_MS,
            lambda: fail(TimeoutError(f"[{action}] to [{target}] timed out")),
            f"timeout of {action} to {target}")

    def _schedule_follower_checks(self):
        if self._stopped or self.mode != Mode.LEADER:
            return
        epoch = self._election_epoch

        def run():
            if self._stopped or self.mode != Mode.LEADER \
                    or epoch != self._election_epoch:
                return
            for peer in sorted(self.applied_state.nodes):
                if peer == self.node_id:
                    continue
                self._check_follower(peer)
            self.scheduler.schedule_delayed(
                FOLLOWER_CHECK_INTERVAL_MS, run, "follower checks")

        self.scheduler.schedule_delayed(FOLLOWER_CHECK_INTERVAL_MS, run,
                                        "follower checks")

    def _check_follower(self, peer: str):
        def on_ok(resp):
            self._check_failures[peer] = 0

        def on_fail(exc):
            if self.mode != Mode.LEADER:
                return
            self._check_failures[peer] = self._check_failures.get(peer, 0) + 1
            if self._check_failures[peer] >= CHECK_RETRY_COUNT:
                self._remove_node(peer, "followers check retry count "
                                        "exceeded")

        self._send_with_timeout(peer, FOLLOWER_CHECK_ACTION,
                                {"term": self.coord_state.current_term},
                                on_ok, on_fail)

    def _on_follower_check(self, sender: str, payload: dict):
        """FollowersChecker.handleFollowerCheck: a check from a leader with
        a current term makes us its follower."""
        if not self.health():
            # FollowersChecker treats a NodeHealthCheckFailureException
            # as an immediate-removal failure class; here it counts a
            # strike like any other check failure
            raise CoordinationStateRejectedError(
                f"node [{self.node_id}] is unhealthy (fs probe failed)")
        term = payload["term"]
        if term < self.coord_state.current_term:
            raise CoordinationStateRejectedError(
                f"rejecting check from leader in term {term}, current term "
                f"is {self.coord_state.current_term}")
        if term > self.coord_state.current_term:
            self.coord_state.handle_start_join(
                StartJoinRequest(source_node=sender, term=term))
        if self.mode != Mode.FOLLOWER or self.leader != sender:
            self._become_follower(sender)
        return {"ok": True}

    def _remove_node(self, peer: str, reason: str):
        """NodeRemovalClusterStateTaskExecutor analog."""
        self._check_failures.pop(peer, None)

        def update(state: ClusterState) -> ClusterState:
            return state.with_(nodes=frozenset(set(state.nodes) - {peer}))

        self.submit_state_update(update)

    def _schedule_leader_check(self):
        if self._stopped or self.mode != Mode.FOLLOWER:
            return
        epoch = self._election_epoch

        def run():
            if self._stopped or self.mode != Mode.FOLLOWER \
                    or epoch != self._election_epoch:
                return
            leader = self.leader

            def on_ok(resp):
                self._leader_check_failures = 0

            def on_fail(exc):
                if self.mode != Mode.FOLLOWER or self.leader != leader:
                    return
                self._leader_check_failures += 1
                if self._leader_check_failures >= CHECK_RETRY_COUNT:
                    self._become_candidate("leader check retry count "
                                           "exceeded")

            self._send_with_timeout(leader, LEADER_CHECK_ACTION,
                                    {}, on_ok, on_fail)
            self.scheduler.schedule_delayed(LEADER_CHECK_INTERVAL_MS, run,
                                            "leader check")

        self.scheduler.schedule_delayed(LEADER_CHECK_INTERVAL_MS, run,
                                        "leader check")

    def _on_leader_check(self, sender: str, payload: dict):
        if self.mode != Mode.LEADER:
            raise CoordinationStateRejectedError(
                f"rejecting leader check while mode is {self.mode.value}")
        if sender not in self.applied_state.nodes:
            # LeaderChecker's removed-node rejection: a node we removed
            # (e.g. failed health checks) must learn it is out — its
            # leader-check failures then turn it candidate, and its next
            # pre-vote round rejoins via the leader hint once healthy
            raise CoordinationStateRejectedError(
                f"rejecting leader check from [{sender}] which is not in "
                f"the current cluster membership")
        return {"ok": True}

    # -------------------------------------------------------------- joining

    def join_cluster(self, via: str):
        """A fresh node asks `via` (any known node) to admit it."""
        def on_response(resp):
            if resp and not resp.get("accepted") and resp.get("leader"):
                self.join_cluster(resp["leader"])
                return
            if resp and resp.get("accepted") and \
                    resp.get("term", 0) > self.coord_state.current_term:
                # adopt the leader's term and hand it our join vote so the
                # voting configuration can grow to include this node
                try:
                    join = self.coord_state.handle_start_join(
                        StartJoinRequest(source_node=via,
                                         term=resp["term"]))
                except CoordinationStateRejectedError:
                    return
                self.transport.send(
                    self.node_id, via, JOIN_ACTION,
                    {"join": (join.source_node, join.target_node, join.term,
                              join.last_accepted_term,
                              join.last_accepted_version)},
                    None, lambda e: None)

        self.known_peers.add(via)
        self.transport.send(self.node_id, via, JOIN_ACTION, {},
                            on_response, lambda e: None)


def bootstrap_state(node_ids: List[str]) -> ClusterState:
    """ClusterBootstrapService analog: the initial voting configuration is
    the explicit list of master-eligible nodes (initial_cluster_manager_nodes)."""
    config = VotingConfiguration(frozenset(node_ids))
    return ClusterState(term=0, version=0, nodes=frozenset(node_ids),
                        master_node=None,
                        last_committed_config=config,
                        last_accepted_config=config,
                        data=None)
