"""Shard allocation: assign primaries and replicas to live nodes.

Re-design of the reference allocator stack — BalancedShardsAllocator
(cluster/routing/allocation/allocator/BalancedShardsAllocator.java:85)
weight-balancing shard counts per node, gated by the decider chain
(cluster/routing/allocation/decider/SameShardAllocationDecider.java — at
most one copy of a shard per node) — collapsed into one pure function over
the cluster-state payload. The reference's RoutingTable/ShardRouting
object model becomes the plain-dict `routing` table carried in
ClusterState.data (serialized by transport/serde.py):

  routing[index] = [            # one entry per shard id
    {"primary": node_id | None, # assigned primary copy
     "primary_term": int,       # bumped on every promotion/assignment
     "replicas": [node_id...],  # assigned replica copies
     "active_replicas": [...]}, # recovered, in-sync copies (subset)
  ]

Promotion on primary loss picks from active_replicas — the in-sync-
allocation-ids rule (cluster/metadata/IndexMetadata "in_sync_allocations"
+ gateway/PrimaryShardAllocator.java:80): only a copy that finished
recovery may become primary, never a stale or initializing one.
"""

from __future__ import annotations

import copy
from typing import Dict, List, Optional


def _copy_counts(routing: Dict[str, List[dict]], live: List[str]
                 ) -> Dict[str, int]:
    counts = {n: 0 for n in live}
    for shards in routing.values():
        for entry in shards:
            for n in [entry.get("primary")] + entry.get("replicas", []):
                if n in counts:
                    counts[n] += 1
    return counts


def _least_loaded(counts: Dict[str, int], exclude: set) -> Optional[str]:
    candidates = [(c, n) for n, c in counts.items() if n not in exclude]
    if not candidates:
        return None
    candidates.sort()
    return candidates[0][1]


def allocate(data: dict, live_nodes: List[str]) -> dict:
    """Compute a new routing table for `data` given the live node set.

    Pure: returns a new data dict (cluster states are immutable values).
    Handles initial allocation, node-left cleanup, replica promotion, and
    replica count reconciliation. Idempotent: allocating an already-
    balanced table is a no-op (callers diff to decide whether to publish).
    """
    data = copy.deepcopy(data)
    live = sorted(set(live_nodes))
    indices: Dict[str, dict] = data.get("indices", {})
    routing: Dict[str, List[dict]] = data.setdefault("routing", {})

    # drop routing for deleted indices
    for name in list(routing):
        if name not in indices:
            del routing[name]

    counts = _copy_counts(routing, live)

    for name, meta in indices.items():
        settings = meta.get("settings", {})
        num_shards = int(settings.get("number_of_shards", 1))
        num_replicas = int(settings.get("number_of_replicas", 0))
        shards = routing.setdefault(name, [])
        while len(shards) < num_shards:
            shards.append({"primary": None, "primary_term": 0,
                           "replicas": [], "active_replicas": []})
        for entry in shards:
            live_set = set(live)
            # scrub dead nodes
            entry["replicas"] = [n for n in entry["replicas"]
                                 if n in live_set]
            entry["active_replicas"] = [n for n in entry["active_replicas"]
                                        if n in live_set]
            if entry["primary"] not in live_set:
                entry["primary"] = None
            # promote or assign a primary
            if entry["primary"] is None:
                if entry["active_replicas"]:
                    promoted = entry["active_replicas"][0]
                    entry["primary"] = promoted
                    entry["replicas"] = [n for n in entry["replicas"]
                                         if n != promoted]
                    entry["active_replicas"] = [
                        n for n in entry["active_replicas"] if n != promoted]
                    entry["primary_term"] += 1
                elif not entry["replicas"]:
                    # no copies exist anywhere: fresh (empty) primary —
                    # only safe when the shard has never been allocated
                    # (term 0); otherwise wait for a copy to return
                    if entry["primary_term"] == 0:
                        node = _least_loaded(counts, set())
                        if node is not None:
                            entry["primary"] = node
                            entry["primary_term"] = 1
                            counts[node] += 1
                # replicas still initializing (not active) can't be
                # promoted — shard stays red until one activates
            # reconcile replica count
            holders = {entry["primary"]} | set(entry["replicas"])
            holders.discard(None)
            while (len(entry["replicas"]) < num_replicas
                   and entry["primary"] is not None):
                node = _least_loaded(counts, holders)
                if node is None:
                    break
                entry["replicas"].append(node)
                holders.add(node)
                counts[node] += 1
            while len(entry["replicas"]) > num_replicas:
                dropped = entry["replicas"].pop()
                entry["active_replicas"] = [
                    n for n in entry["active_replicas"] if n != dropped]
                if dropped in counts:
                    counts[dropped] -= 1
    return data


def shard_copies(entry: dict) -> List[str]:
    """All nodes holding a copy of the shard (primary first)."""
    out = []
    if entry.get("primary"):
        out.append(entry["primary"])
    out.extend(entry.get("replicas", []))
    return out


def health_of(data: dict) -> str:
    """green = every copy assigned+active; yellow = all primaries active
    but some replicas missing; red = an unassigned primary exists."""
    status = "green"
    for shards in (data.get("routing") or {}).values():
        for entry in shards:
            if entry.get("primary") is None:
                return "red"
            want = len(entry.get("replicas", []))
            have = len(entry.get("active_replicas", []))
            if have < want:
                status = "yellow"
    return status
