"""Shard allocation: assign primaries and replicas to live nodes.

Re-design of the reference allocator stack — BalancedShardsAllocator
(cluster/routing/allocation/allocator/BalancedShardsAllocator.java:85)
weight-balancing shard counts per node, gated by the decider chain
(cluster/routing/allocation/decider/*, see deciders.py) — collapsed into one
pure function over the cluster-state payload. The reference's
RoutingTable/ShardRouting object model becomes the plain-dict `routing`
table carried in ClusterState.data (serialized by transport/serde.py):

  routing[index] = [            # one entry per shard id
    {"primary": node_id | None, # assigned primary copy
     "primary_term": int,       # bumped on every promotion/assignment
     "replicas": [node_id...],  # assigned replica copies
     "active_replicas": [...],  # recovered, in-sync copies (subset)
     "relocating": {...}?},     # in-flight move (see below)
  ]

Promotion on primary loss picks from active_replicas — the in-sync-
allocation-ids rule (cluster/metadata/IndexMetadata "in_sync_allocations"
+ gateway/PrimaryShardAllocator.java:80): only a copy that finished
recovery may become primary, never a stale or initializing one.

Relocation (rebalancing and filter-driven moves) is two-phase, exactly the
reference's RELOCATING → recovery → handoff dance: phase 1 assigns the
target as an extra initializing replica and records
``entry["relocating"] = {"from": n, "to": m, "primary": bool}``; phase 2
(a later reroute, after the target's recovery completes and `shard_started`
lands it in active_replicas) promotes the target (for primary moves, with a
term bump) and drops the source copy. Data is never dropped before the new
copy is active.
"""

from __future__ import annotations

import copy
from typing import Dict, List, Optional

from opensearch_tpu.cluster.deciders import (
    AllocationContext, NO, THROTTLE, can_allocate, can_rebalance, can_remain)


def allocate(data: dict, live_nodes: List[str]) -> dict:
    """Compute a new routing table for `data` given the live node set.

    Pure: returns a new data dict (cluster states are immutable values).
    Handles initial allocation, node-left cleanup, replica promotion,
    replica count reconciliation, decider enforcement (canRemain moves),
    relocation completion, and weight-based rebalancing. Idempotent:
    allocating an already-balanced table is a no-op (callers diff to
    decide whether to publish).
    """
    data = copy.deepcopy(data)
    live = sorted(set(live_nodes))
    live_set = set(live)
    indices: Dict[str, dict] = data.get("indices", {})
    routing: Dict[str, List[dict]] = data.setdefault("routing", {})

    # drop routing for deleted indices
    for name in list(routing):
        if name not in indices:
            del routing[name]

    # ---------------------------------------------------- scrub dead nodes
    for name, shards in routing.items():
        for entry in shards:
            entry["replicas"] = [n for n in entry["replicas"]
                                 if n in live_set]
            entry["active_replicas"] = [n for n in entry["active_replicas"]
                                        if n in live_set]
            if entry["primary"] not in live_set:
                entry["primary"] = None
            rel = entry.get("relocating")
            if rel and (rel["to"] not in entry["replicas"]
                        or (rel.get("primary")
                            and entry["primary"] != rel["from"])):
                # target died, or the source primary is gone (normal
                # promotion takes over) — abandon the move
                entry.pop("relocating", None)

    ctx = AllocationContext(data, live)

    for name in sorted(indices):
        meta = indices.get(name) or {}
        settings = meta.get("settings", {})
        num_shards = int(settings.get("number_of_shards", 1))
        num_replicas = int(settings.get("number_of_replicas", 0))
        shards = routing.setdefault(name, [])
        while len(shards) < num_shards:
            shards.append({"primary": None, "primary_term": 0,
                           "replicas": [], "active_replicas": []})
        for entry in shards:
            _complete_relocation(ctx, name, entry)
            # promotion BEFORE decider enforcement: a vetoed node's active
            # replica may be the last in-sync copy of a primary-less shard —
            # it must become primary (and then relocate copy-first), never
            # be dropped
            _assign_primary(ctx, name, entry)
            _enforce_can_remain(ctx, name, entry)
            _reconcile_replicas(ctx, name, entry, num_replicas)

    _rebalance(ctx, routing)
    return data


# ------------------------------------------------------------- per-shard ops

def _complete_relocation(ctx: AllocationContext, index: str, entry: dict):
    """Phase 2: the relocation target finished recovery — hand off."""
    rel = entry.get("relocating")
    if not rel:
        return
    target, source = rel["to"], rel["from"]
    if target not in entry.get("active_replicas", []):
        return                          # still recovering; keep waiting
    if rel.get("primary"):
        # handoff: promote the recovered target, retire the source copy
        entry["primary"] = target
        entry["primary_term"] = entry.get("primary_term", 0) + 1
        entry["replicas"] = [n for n in entry["replicas"] if n != target]
        entry["active_replicas"] = [n for n in entry["active_replicas"]
                                    if n != target]
        ctx.remove_copy(source, index)
    else:
        entry["replicas"] = [n for n in entry["replicas"] if n != source]
        entry["active_replicas"] = [n for n in entry["active_replicas"]
                                    if n != source]
        ctx.remove_copy(source, index)
    entry.pop("relocating", None)


def _enforce_can_remain(ctx: AllocationContext, index: str, entry: dict):
    """Move copies off nodes the deciders veto (filter changes, disk high
    watermark): replicas drop and re-allocate; a primary relocates (copy
    first, never drop data)."""
    for node in list(entry.get("replicas", [])):
        rel = entry.get("relocating") or {}
        if rel.get("to") == node or rel.get("from") == node:
            # both endpoints of an in-flight relocation are judged once
            # the move completes — dropping the source here would leave a
            # stale `relocating` record that inflates the replica want
            # count and double-removes the copy at _complete_relocation
            continue
        if can_remain(ctx, index, entry, node, is_primary=False).kind == NO:
            was_initializing = node not in entry.get("active_replicas", [])
            entry["replicas"] = [n for n in entry["replicas"] if n != node]
            entry["active_replicas"] = [n for n in entry["active_replicas"]
                                        if n != node]
            ctx.remove_copy(node, index, initializing=was_initializing)
    primary = entry.get("primary")
    if primary is None or entry.get("relocating"):
        return
    if can_remain(ctx, index, entry, primary, is_primary=True).kind != NO:
        return
    # prefer an immediate swap with an active replica on a permitted node
    for candidate in entry.get("active_replicas", []):
        if can_remain(ctx, index, entry, candidate, is_primary=True):
            entry["primary"] = candidate
            entry["primary_term"] = entry.get("primary_term", 0) + 1
            entry["replicas"] = [n for n in entry["replicas"]
                                 if n != candidate]
            entry["active_replicas"] = [n for n in entry["active_replicas"]
                                        if n != candidate]
            ctx.remove_copy(primary, index)
            return
    # otherwise start a relocation to the best permitted node
    target = _best_node(ctx, index, entry, is_primary=True)
    if target is not None:
        _start_relocation(ctx, index, entry, primary, target, primary=True)


def _assign_primary(ctx: AllocationContext, index: str, entry: dict):
    if entry.get("primary") is not None:
        return
    if entry.get("active_replicas"):
        promoted = entry["active_replicas"][0]
        entry["primary"] = promoted
        entry["replicas"] = [n for n in entry["replicas"] if n != promoted]
        entry["active_replicas"] = [n for n in entry["active_replicas"]
                                    if n != promoted]
        entry["primary_term"] = entry.get("primary_term", 0) + 1
        return
    if entry.get("replicas"):
        # replicas still initializing (not active) can't be promoted —
        # shard stays red until one activates
        return
    # no copies exist anywhere: fresh (empty) primary — only safe when the
    # shard has never been allocated (term 0); otherwise wait for a copy
    if entry.get("primary_term", 0) == 0:
        node = _best_node(ctx, index, entry, is_primary=True)
        if node is not None:
            entry["primary"] = node
            entry["primary_term"] = 1
            ctx.add_copy(node, index, initializing=False)


def _reconcile_replicas(ctx: AllocationContext, index: str, entry: dict,
                        num_replicas: int):
    rel = entry.get("relocating")
    want = num_replicas + (1 if rel else 0)  # the move target is extra
    while (len(entry["replicas"]) < want
           and entry.get("primary") is not None):
        node = _best_node(ctx, index, entry, is_primary=False)
        if node is None:
            break
        entry["replicas"].append(node)
        ctx.add_copy(node, index, initializing=True)
    protected = {rel["to"]} if rel else set()
    extra = [n for n in entry["replicas"] if n not in protected]
    while len(entry["replicas"]) > want and extra:
        dropped = extra.pop()
        was_initializing = dropped not in entry.get("active_replicas", [])
        entry["replicas"] = [n for n in entry["replicas"] if n != dropped]
        entry["active_replicas"] = [n for n in entry["active_replicas"]
                                    if n != dropped]
        ctx.remove_copy(dropped, index, initializing=was_initializing)


def _best_node(ctx: AllocationContext, index: str, entry: dict,
               is_primary: bool) -> Optional[str]:
    """The permitted node minimizing the balance weight
    (BalancedShardsAllocator.Balancer#weight): THROTTLE skips this pass —
    the next reroute (every state change triggers one) retries."""
    best, best_w = None, None
    for node in ctx.live:
        d = can_allocate(ctx, index, entry, node, is_primary)
        if d.kind in (NO, THROTTLE):
            continue
        w = _weight(ctx, node, index)
        if best_w is None or (w, node) < (best_w, best):
            best, best_w = node, w
    return best


def _weight(ctx: AllocationContext, node: str, index: str) -> float:
    shard_b = float(ctx.cluster_setting(
        "cluster.routing.allocation.balance.shard", 0.45))
    index_b = float(ctx.cluster_setting(
        "cluster.routing.allocation.balance.index", 0.55))
    return (shard_b * ctx.node_copies.get(node, 0)
            + index_b * ctx.node_index_copies.get((node, index), 0))


def _start_relocation(ctx: AllocationContext, index: str, entry: dict,
                      source: str, target: str, primary: bool):
    entry["relocating"] = {"from": source, "to": target, "primary": primary}
    entry["replicas"] = entry.get("replicas", []) + [target]
    ctx.add_copy(target, index, initializing=True)
    # count the source as leaving so balance math sees the post-move world
    ctx.remove_copy(source, index)


# --------------------------------------------------------------- rebalancing

def _rebalance(ctx: AllocationContext, routing: Dict[str, List[dict]]):
    """One balancing pass: while an index's node-weight spread exceeds the
    threshold, relocate one copy from the heaviest to the lightest permitted
    node, up to cluster_concurrent_rebalance in-flight moves."""
    if len(ctx.live) < 2:
        return
    max_moves = int(ctx.cluster_setting(
        "cluster.routing.allocation.cluster_concurrent_rebalance", 2))
    in_flight = sum(1 for shards in routing.values()
                    for e in shards if e.get("relocating"))
    threshold = float(ctx.cluster_setting(
        "cluster.routing.allocation.balance.threshold", 1.0))
    for index in sorted(routing):
        while in_flight < max_moves:
            ranked = sorted(ctx.live, key=lambda n: (_weight(ctx, n, index), n))
            lightest, heaviest = ranked[0], ranked[-1]
            if _weight(ctx, heaviest, index) \
                    - _weight(ctx, lightest, index) <= threshold:
                break
            moved = _move_one(ctx, routing[index], index, heaviest, lightest)
            if not moved:
                break
            in_flight += 1


def _move_one(ctx: AllocationContext, shards: List[dict], index: str,
              source: str, target: str) -> bool:
    for entry in shards:
        if entry.get("relocating"):
            continue
        is_primary = entry.get("primary") == source
        holds = is_primary or source in entry.get("replicas", [])
        if not holds:
            continue
        if not can_rebalance(ctx, moving_primary=is_primary):
            continue
        if not can_allocate(ctx, index, entry, target, is_primary):
            continue
        _start_relocation(ctx, index, entry, source, target,
                          primary=is_primary)
        return True
    return False


# -------------------------------------------------------- reroute commands

def apply_reroute_command(data: dict, live: List[str], cmd: dict) -> None:
    """One explicit _cluster/reroute command (cluster/routing/allocation/
    command/*Command.java): move, cancel, allocate_replica,
    allocate_empty_primary, allocate_stale_primary. Mutates data["routing"]
    in place; the caller's allocate() pass then completes/validates the
    result. Invalid commands raise IllegalArgumentError (HTTP 400)."""
    from opensearch_tpu.common.errors import IllegalArgumentError
    if not isinstance(cmd, dict) or len(cmd) != 1:
        raise IllegalArgumentError(
            "[reroute] each command must have exactly one verb")
    verb, args = next(iter(cmd.items()))
    if not isinstance(args, dict):
        raise IllegalArgumentError(f"[reroute] [{verb}] expects an object")
    index = args.get("index")
    try:
        shard = int(args.get("shard", 0))
    except (TypeError, ValueError):
        raise IllegalArgumentError(
            f"[reroute] [shard] must be an integer, got "
            f"[{args.get('shard')}]")
    routing = data.get("routing", {})
    if index not in routing or not 0 <= shard < len(routing[index]):
        raise IllegalArgumentError(
            f"[reroute] no such shard [{index}][{shard}]")
    entry = routing[index][shard]
    ctx = AllocationContext(data, live)
    live_set = set(live)

    def require_node(name: str):
        node = args.get(name)
        if not node:
            raise IllegalArgumentError(f"[reroute] [{verb}] requires "
                                       f"[{name}]")
        if node not in live_set:
            raise IllegalArgumentError(
                f"[reroute] no such node [{node}] in the cluster")
        return node

    if verb == "move":
        source, target = require_node("from_node"), require_node("to_node")
        if entry.get("relocating"):
            raise IllegalArgumentError(
                f"[reroute] shard [{index}][{shard}] is already relocating")
        is_primary = entry.get("primary") == source
        if not is_primary and source not in entry.get("replicas", []):
            raise IllegalArgumentError(
                f"[reroute] [{source}] holds no copy of "
                f"[{index}][{shard}]")
        decision = can_allocate(ctx, index, entry, target, is_primary)
        if decision.kind == NO:
            raise IllegalArgumentError(
                f"[reroute] cannot allocate [{index}][{shard}] to "
                f"[{target}]: {decision.reason}")
        _start_relocation(ctx, index, entry, source, target,
                          primary=is_primary)
    elif verb == "cancel":
        node = args.get("node")
        if not node:
            raise IllegalArgumentError("[reroute] [cancel] requires [node]")
        if entry.get("primary") == node:
            if not args.get("allow_primary"):
                raise IllegalArgumentError(
                    "[reroute] cancelling the primary requires "
                    "[allow_primary: true]")
            entry["primary"] = None
        elif node in entry.get("replicas", []):
            entry["replicas"] = [n for n in entry["replicas"] if n != node]
            entry["active_replicas"] = [n for n in entry["active_replicas"]
                                        if n != node]
            rel = entry.get("relocating")
            if rel and node in (rel["from"], rel["to"]):
                entry.pop("relocating", None)
        else:
            raise IllegalArgumentError(
                f"[reroute] [{node}] holds no copy of [{index}][{shard}]")
    elif verb == "allocate_replica":
        node = require_node("node")
        if entry.get("primary") is None:
            raise IllegalArgumentError(
                f"[reroute] [{index}][{shard}] has no active primary to "
                f"recover a replica from")
        desired = int(((data.get("indices", {}).get(index) or {})
                       .get("settings") or {}).get("number_of_replicas", 0))
        if len(entry.get("replicas", [])) >= desired:
            raise IllegalArgumentError(
                f"[reroute] all [{desired}] replica copies of "
                f"[{index}][{shard}] are already allocated")
        if node in shard_copies(entry):
            raise IllegalArgumentError(
                f"[reroute] [{node}] already holds a copy of "
                f"[{index}][{shard}]")
        decision = can_allocate(ctx, index, entry, node, is_primary=False)
        if decision.kind == NO:
            raise IllegalArgumentError(
                f"[reroute] cannot allocate replica to [{node}]: "
                f"{decision.reason}")
        entry["replicas"] = entry.get("replicas", []) + [node]
    elif verb in ("allocate_empty_primary", "allocate_stale_primary"):
        node = require_node("node")
        if not args.get("accept_data_loss"):
            raise IllegalArgumentError(
                f"[reroute] [{verb}] requires [accept_data_loss: true]")
        if entry.get("primary") is not None:
            raise IllegalArgumentError(
                f"[reroute] [{index}][{shard}] already has a primary")
        entry["primary"] = node
        entry["primary_term"] = entry.get("primary_term", 0) + 1
        entry["replicas"] = [n for n in entry.get("replicas", [])
                             if n != node]
        entry["active_replicas"] = [n for n in entry.get("active_replicas",
                                                         []) if n != node]
    else:
        raise IllegalArgumentError(f"[reroute] unknown command [{verb}]")


# ------------------------------------------------------------------- queries

def shard_copies(entry: dict) -> List[str]:
    """All nodes holding a copy of the shard (primary first)."""
    out = []
    if entry.get("primary"):
        out.append(entry["primary"])
    out.extend(entry.get("replicas", []))
    return out


def health_of(data: dict) -> str:
    """green = every copy assigned+active; yellow = all primaries active
    but some replicas missing; red = an unassigned primary exists."""
    status = "green"
    for shards in (data.get("routing") or {}).values():
        for entry in shards:
            if entry.get("primary") is None:
                return "red"
            want = len(entry.get("replicas", []))
            have = len(entry.get("active_replicas", []))
            if have < want:
                status = "yellow"
    return status
