"""ClusterNode: a full cluster member — coordination + routing + data.

This is the integration layer round 1 lacked: it joins the three islands
(Coordinator over TcpTransport, the shard engine, and the REST surface)
into one distributed system, the way the reference wires them in
node/Node.java:1180 (start sequence), with:

  - cluster state (ClusterState.data) carrying index metadata + the
    routing table (cluster/ClusterState.java:167 {Metadata, RoutingTable}),
  - a state→local-shards apply loop (IndicesClusterStateService.java:120),
  - primary-backup write replication over the transport
    (TransportReplicationAction.java / ReplicationOperation.java:175),
  - peer recovery over the transport (RecoverySourceHandler.java:164 —
    segment copy + tracked-op catch-up), and
  - scatter-gather search over the transport (TransportSearchAction.java:
    284 → per-shard query phase → fetch phase → coordinator reduce).

TPU-first notes: the data plane stays columnar — per-shard query phases
run the jitted plan pipeline locally on each node's device and ship only
top-k candidates + decoded agg partials (numpy) back; segments cross the
wire once at recovery (Opaque frames), never per query.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from opensearch_tpu.cluster.allocation import allocate, health_of, shard_copies
from opensearch_tpu.cluster.coordination.coordinator import (
    Coordinator, Mode, NotLeaderAbort, bootstrap_state)
from opensearch_tpu.cluster.coordination.core import ClusterState
from opensearch_tpu.cluster.routing import generate_shard_id
from opensearch_tpu.common.errors import (
    IllegalArgumentError, IndexNotFoundError, OpenSearchTpuError,
    ProcessClusterEventTimeoutError, RemoteTransportError,
    ShardNotReadyError)
from opensearch_tpu.index.mapper import MapperService
from opensearch_tpu.index.shard import IndexShard
from opensearch_tpu.transport.serde import Opaque
from opensearch_tpu.transport.tcp import TcpTransport

# transport action names (reference: ActionModule registrations)
SHARD_BULK_PRIMARY = "indices:data/write/shard_bulk[p]"
SHARD_BULK_REPLICA = "indices:data/write/shard_bulk[r]"
SHARD_QUERY = "indices:data/read/search[phase/query]"
SHARD_FETCH = "indices:data/read/search[phase/fetch]"
SHARD_DFS = "indices:data/read/search[phase/dfs]"
SHARD_GET = "indices:data/read/get[s]"
SHARD_REFRESH = "indices:admin/refresh[s]"
START_RECOVERY = "internal:index/shard/recovery/start_recovery"
RECOVERY_CHUNK = "internal:index/shard/recovery/file_chunk"
RECOVERY_DONE = "internal:index/shard/recovery/finalize"
RECOVERY_CHUNK_BYTES = 512 * 1024    # reference CHUNK_SIZE (512KB)
# process-wide ops-vs-file recovery counters (recovery stats surface)
RECOVERY_STATS: Dict[str, int] = {"ops": 0, "file": 0}
LEADER_UPDATE = "internal:cluster/leader_update"
REGISTER_ADDR = "internal:cluster/register_address"
# cross-cluster search (reference: RemoteClusterService.java:80 +
# TransportSearchAction.java:422 ccsRemoteReduce): the remote cluster's
# coordinator runs its own scatter + partial collection and returns
# candidates + agg partials; the local coordinator merges
CCS_QUERY = "indices:data/read/search[ccs/query]"
CCS_FETCH = "indices:data/read/search[ccs/fetch]"


class NotLeaderError(OpenSearchTpuError):
    status = 503
    error_type = "cluster_manager_not_discovered_exception"


def _unwrap(value):
    """Local (same-node) action calls skip serde, so Opaque payloads
    arrive still wrapped; remote calls arrive decoded. Normalize."""
    return value.value if isinstance(value, Opaque) else value


class ClusterNode:
    """One cluster member. Duck-types Node's handle()/request() so the
    HTTP server and tests drive it identically; data-plane routes are
    routed cluster-wide, the rest falls through to the local Node."""

    def __init__(self, node_id: str, host: str = "127.0.0.1", port: int = 0,
                 settings: Optional[dict] = None):
        from opensearch_tpu.node import Node
        self.node_id = node_id
        self.settings = settings or {}
        # node.attr.* settings become allocation-visible attributes
        # (reference: DiscoveryNode attributes consumed by the awareness
        # and filter deciders)
        self.attrs = {k[len("node.attr."):]: str(v)
                      for k, v in self.settings.items()
                      if k.startswith("node.attr.")}
        # node-level data path (path.data): cluster shards get durable
        # stores + translogs under it, which is what makes ops-based
        # (sequence-number) peer recovery possible over the transport
        self.data_path = self.settings.get("path.data")
        self.local = Node(node_name=node_id, settings=settings)
        # TLS + join-secret config from node settings (transport/security)
        from opensearch_tpu.transport.security import SecurityConfig
        self.security = SecurityConfig(settings)
        # one named-pool registry per node, shared by the transport's
        # handler dispatch and the REST layer (ThreadPool.java:92)
        self.transport = TcpTransport(node_id, host=host, port=port,
                                      threadpool=self.local.threadpool,
                                      security=self.security)
        self.shards: Dict[Tuple[str, int], IndexShard] = {}
        # keyed by (index name, index UUID) — see _mapper_for
        self._mappers: Dict[Tuple[str, Optional[str]], MapperService] = {}
        # replicas the local primary must fan ops to before they appear in
        # active_replicas (recovery tracking window — ReplicationTracker's
        # "tracked" allocations, index/seqno/ReplicationTracker.java:103)
        self._tracked: Dict[Tuple[str, int], set] = {}
        self._tracked_lock = threading.Lock()
        self._applied_lock = threading.Lock()
        # adaptive replica selection state (ResponseCollectorService.java:
        # 59): per-node EWMA of query-phase service time + in-flight count;
        # the routing rank is (outstanding+1) * ewma_ms, C3-style
        self._ars: Dict[str, List[float]] = {}   # node -> [ewma_ms, outstanding]
        self._ars_lock = threading.Lock()
        self._ars_rr = 0
        # remote clusters (RemoteClusterService): alias → transport node
        # key of the remote seed; populated via cluster.remote.*.seeds
        self._remotes: Dict[str, str] = {}
        self._latest_state: Optional[ClusterState] = None
        self._reconcile_scheduled = False
        self.coordinator: Optional[Coordinator] = None
        self._started = False
        # persistent tasks (PersistentTasksNodeService analog)
        from opensearch_tpu.cluster.persistent import PersistentTaskRunner
        self.persistent_tasks = PersistentTaskRunner(self)
        # in-flight chunked-recovery sessions (source side): session id →
        # serialized segment blobs awaiting chunk pulls
        self._recovery_sessions: Dict[str, dict] = {}
        # shards currently re-recovering an EXISTING local copy (the
        # initializing-but-present reconcile path); guards double submits
        self._rerecovering: set = set()
        # shards whose shard_started is submitted but not yet visible in
        # active_replicas — skip redundant re-recoveries in that window
        self._started_pending: set = set()

    # ------------------------------------------------------------ lifecycle

    @property
    def address(self) -> Tuple[str, int]:
        return self.transport.address

    def bootstrap(self, peers: Dict[str, Tuple[str, int]]):
        """Form a new cluster from an explicit initial voting configuration
        (cluster.initial_cluster_manager_nodes). `peers` maps node_id →
        (host, port) for every bootstrap member including self."""
        for nid, addr in peers.items():
            if nid != self.node_id:
                self.transport.add_address(nid, *addr)
        initial = bootstrap_state(sorted(peers))
        initial = initial.with_(data={
            "indices": {}, "routing": {},
            "addresses": {n: list(a) for n, a in peers.items()}})
        self._start_coordinator(initial)

    def join(self, seed: Tuple[str, int], seed_id: str):
        """Join an existing cluster via a seed host (discovery seed_hosts).

        The cluster must be able to dial us back before the leader can
        publish state to us, so the first step hands our transport address
        to the seed (HandshakingTransportAddressConnector's role)."""
        self.transport.add_address(seed_id, *seed)
        self._start_coordinator(ClusterState())
        resp = self.transport.send_sync(
            seed_id, REGISTER_ADDR,
            {"node": self.node_id, "addr": list(self.address),
             "attrs": self.attrs},
            timeout=10.0)
        # learn the cluster's address book so a leader-redirect from the
        # seed ("accepted": False, "leader": X) can actually be followed
        for nid, addr in (resp.get("addresses") or {}).items():
            if nid != self.node_id:
                self.transport.add_address(nid, *addr)
        self.coordinator.join_cluster(seed_id)

    def _start_coordinator(self, initial: ClusterState):
        self._register_actions()
        from opensearch_tpu.monitor import FsHealthService
        self.fs_health = FsHealthService(self.data_path).start()
        self.coordinator = Coordinator(
            self.node_id, self.transport, self.transport.scheduler, initial,
            on_state_applied=self._on_state_applied,
            health=lambda: self.fs_health.healthy)
        self.coordinator.start()
        self._started = True

    def close(self):
        self._started = False
        if getattr(self, "fs_health", None) is not None:
            self.fs_health.stop()
        self.persistent_tasks.shutdown()
        if self.coordinator is not None:
            self.coordinator.stop()
        self.transport.close()
        self.local.threadpool.shutdown()
        for shard in self.shards.values():
            shard.close()

    # --------------------------------------------------------- leader logic

    @property
    def is_leader(self) -> bool:
        return (self.coordinator is not None
                and self.coordinator.mode == Mode.LEADER)

    @property
    def state(self) -> Optional[ClusterState]:
        if self.coordinator is None:
            return None
        return self.coordinator.applied_state

    def _data(self) -> dict:
        st = self.state
        return (st.data or {}) if st is not None else {}

    def _leader_id(self) -> Optional[str]:
        if self.coordinator is None:
            return None
        if self.is_leader:
            return self.node_id
        return self.coordinator.leader

    def _submit_to_leader(self, update: dict, timeout: float = 30.0) -> dict:
        """Route a cluster-state mutation to the elected leader
        (TransportMasterNodeAction) and wait for it to be applied.
        `timeout` bounds when new attempts may START; a single in-flight
        attempt can extend past it (up to ~80s) because aborting mid-wait
        would force a retry that double-enqueues a non-idempotent
        update."""
        deadline = time.time() + timeout
        while time.time() < deadline:
            leader = self._leader_id()
            if leader is None:
                time.sleep(0.05)
                continue
            if leader == self.node_id:
                ok = self._leader_apply_update(update)
            else:
                try:
                    # timeout must exceed the leader-side worst case
                    # (submitted.wait 10s + folded.wait 70s), or a
                    # slow-quorum publication makes the caller retry and
                    # double-enqueue a non-idempotent update
                    resp = self.transport.send_sync(
                        leader, LEADER_UPDATE, update, timeout=85.0)
                    ok = bool(resp and resp.get("accepted"))
                except RemoteTransportError as e:
                    # the leader rejected the update itself (duplicate
                    # create_index) or reported an unresolved publication:
                    # neither is safe to blind-retry against a new leader
                    if e.status < 500 or e.error_type == \
                            ProcessClusterEventTimeoutError.error_type:
                        raise
                    ok = False
                except OpenSearchTpuError:
                    ok = False
            if ok:
                return {"acknowledged": True}
            time.sleep(0.05)
        raise NotLeaderError("timed out routing update to cluster manager")

    def _leader_apply_update(self, update: dict) -> bool:
        """Leader side: fold a typed update into cluster state and publish.
        Runs the mutation inside submit_state_update so it composes with
        concurrent joins/removals (MasterService single-threaded batch)."""
        if not self.is_leader:
            return False

        def mutate(state: ClusterState) -> ClusterState:
            data = dict(state.data or {})
            data.setdefault("indices", {})
            data.setdefault("routing", {})
            data.setdefault("addresses", {})
            kind = update["kind"]
            if kind == "create_index":
                name = update["name"]
                if name in data["indices"]:
                    raise IllegalArgumentError(
                        f"index [{name}] already exists")
                data["indices"] = {**data["indices"],
                                   **{name: update["meta"]}}
            elif kind == "delete_index":
                data["indices"] = {k: v for k, v in data["indices"].items()
                                   if k != update["name"]}
            elif kind in ("close_index", "open_index"):
                name = update["name"]
                if name not in data["indices"]:
                    raise IndexNotFoundError(name)
                meta = dict(data["indices"][name])
                meta["closed"] = kind == "close_index"
                data["indices"] = {**data["indices"], name: meta}
            elif kind == "shard_started":
                name, sid, node = (update["index"], update["shard"],
                                   update["node"])
                routing = copy_routing(data)
                entry = routing[name][sid]
                if node in entry["replicas"] and \
                        node not in entry["active_replicas"]:
                    entry["active_replicas"] = (
                        entry["active_replicas"] + [node])
                data["routing"] = routing
            elif kind == "shard_failed":
                # fail a replica out of the copy set (ReplicationOperation
                # failShardIfNeeded): the allocator below re-adds a fresh
                # replica assignment, which triggers re-recovery
                name, sid, node = (update["index"], update["shard"],
                                   update["node"])
                routing = copy_routing(data)
                entry = routing[name][sid]
                entry["replicas"] = [n for n in entry["replicas"]
                                     if n != node]
                entry["active_replicas"] = [
                    n for n in entry["active_replicas"] if n != node]
                data["routing"] = routing
            elif kind == "remote_clusters":
                merged = dict(data.get("remote_clusters") or {})
                for alias, seed in update["remotes"].items():
                    if seed is None:
                        merged.pop(alias, None)
                    else:
                        merged[alias] = seed
                data["remote_clusters"] = merged
            elif kind == "register_address":
                data["addresses"] = {**data["addresses"],
                                     **{update["node"]: update["addr"]}}
                if update.get("attrs") is not None:
                    data["node_attrs"] = {
                        **(data.get("node_attrs") or {}),
                        update["node"]: update["attrs"]}
            elif kind == "cluster_settings":
                merged = dict(data.get("settings") or {})
                for k, v in update["settings"].items():
                    if v is None:
                        merged.pop(k, None)
                    else:
                        merged[k] = v
                data["settings"] = merged
            elif kind == "reroute":
                from opensearch_tpu.cluster.allocation import (
                    apply_reroute_command)
                data["routing"] = copy_routing(data)
                for cmd in update["commands"]:
                    apply_reroute_command(data, sorted(state.nodes), cmd)
            elif kind == "update_index_settings":
                iname = update["index"]
                if iname in data["indices"]:
                    meta = dict(data["indices"][iname])
                    merged = {**(meta.get("settings") or {})}
                    for k, v in update["settings"].items():
                        if v is None:
                            merged.pop(k, None)
                        else:
                            merged[k] = v
                    meta["settings"] = merged
                    data["indices"] = {**data["indices"], iname: meta}
            elif kind.startswith("persistent_task_"):
                from opensearch_tpu.cluster.persistent import fold_update
                fold_update(data, update)
            data = allocate(data, sorted(state.nodes))
            from opensearch_tpu.cluster.persistent import assign_tasks
            assign_tasks(data, sorted(state.nodes))
            return state.with_(data=data)

        # coordinator methods must run on the event-loop thread; the
        # listener reports the update's fold outcome so a validation
        # failure (e.g. duplicate create_index) surfaces to the caller as
        # the typed exception instead of wedging the publish queue
        submitted = threading.Event()
        folded = threading.Event()
        outcome: list = [None, False]   # [exception, accepted]

        def listener(exc):
            outcome[0] = exc
            folded.set()

        def submit():
            outcome[1] = self.coordinator.submit_state_update(mutate,
                                                              listener)
            submitted.set()

        self.transport.post(submit)
        if not submitted.wait(10.0) or not outcome[1]:
            return False
        # the listener fires exactly once: on fold failure, on commit, or
        # via _fail_pending_updates when leadership is lost. The wait must
        # cover an update queued BEHIND an in-flight publication (up to
        # 30s publish timeout) plus its own publication (another 30s). If
        # it still hasn't resolved, the update may yet commit — raising a
        # non-retryable timeout (never returning False, which would make
        # the caller re-enqueue a non-idempotent update) is the only safe
        # answer (ProcessClusterEventTimeoutException semantics).
        if not folded.wait(70.0):
            raise ProcessClusterEventTimeoutError(
                f"cluster state update [{update.get('kind')}] did not "
                f"resolve within 70s")
        if outcome[0] is not None:
            exc = outcome[0]
            if isinstance(exc, NotLeaderAbort):
                return False    # retry against the new leader
            raise exc if isinstance(exc, OpenSearchTpuError) \
                else OpenSearchTpuError(str(exc))
        return True

    # ----------------------------------------------------------- apply loop

    def _on_state_applied(self, state: ClusterState):
        """Runs on the transport event loop — snapshot the state and hand
        reconciliation to the worker pool (it does recovery round-trips)."""
        with self._applied_lock:
            self._latest_state = state
            if self._reconcile_scheduled:
                return
            self._reconcile_scheduled = True
        self.transport._workers.submit(self._reconcile_loop)

    def _reconcile_loop(self):
        while True:
            with self._applied_lock:
                state = self._latest_state
                self._latest_state = None
                if state is None:
                    self._reconcile_scheduled = False
                    return
            try:
                self._reconcile(state)
            except Exception:  # pragma: no cover - keep the loop alive
                import traceback
                traceback.print_exc()

    def _reconcile(self, state: ClusterState):
        """IndicesClusterStateService.applyClusterState analog: converge
        local shards to the routing table."""
        data = state.data or {}
        indices = data.get("indices", {})
        routing = data.get("routing", {})
        # self-heal node attributes into state (bootstrap members never go
        # through the join REGISTER_ADDR handshake); the fold is idempotent
        if self.attrs and self.node_id in state.nodes and \
                (data.get("node_attrs") or {}).get(self.node_id) != self.attrs:
            self._on_register_address(
                self.node_id, {"node": self.node_id,
                               "addr": list(self.address),
                               "attrs": self.attrs})
        for nid, addr in (data.get("addresses") or {}).items():
            if nid != self.node_id:
                self.transport.add_address(nid, *addr)
        # remote-cluster registry from state: every node can coordinate CCS
        state_remotes = data.get("remote_clusters") or {}
        for alias, seed in state_remotes.items():
            host, port = seed.rsplit(":", 1)
            self.register_remote(alias, host, int(port))
        for alias in [a for a in self._remotes if a not in state_remotes]:
            self.remove_remote(alias)
        # leader-side reroute on membership change (AllocationService.
        # reroute via NodeRemovalClusterStateTaskExecutor / join executor):
        # if the routing table no longer matches the live node set, publish
        # a re-allocation — this is what promotes replicas after a primary's
        # node dies and re-replicates after node loss
        if self.is_leader:
            from opensearch_tpu.cluster.persistent import assign_tasks
            reallocated = allocate(data, sorted(state.nodes))
            assign_tasks(reallocated, sorted(state.nodes))
            if reallocated != data:
                def reroute(s: ClusterState) -> ClusterState:
                    newdata = allocate(dict(s.data or {}), sorted(s.nodes))
                    assign_tasks(newdata, sorted(s.nodes))
                    return s.with_(data=newdata)
                self.transport.post(
                    lambda: self.coordinator.submit_state_update(reroute))
        # persistent tasks: start/cancel executors per the state assignments
        self.persistent_tasks.reconcile(data)
        # remove shards we no longer own (or whose index is gone)
        for (name, sid) in list(self.shards):
            entry = (routing.get(name) or [None] * (sid + 1))[sid] \
                if name in routing and sid < len(routing[name]) else None
            owners = shard_copies(entry) if entry else []
            if name not in indices or self.node_id not in owners:
                shard = self.shards.pop((name, sid))
                shard.close()
                with self._tracked_lock:
                    self._tracked.pop((name, sid), None)
        for stale in [k for k in self._mappers if k[0] not in indices]:
            del self._mappers[stale]
        # prune recovery tracking: drop a target once its recovery has
        # COMPLETED (it is in active_replicas and receives ops via the
        # in-sync set) or its node left the cluster. A target merely
        # absent from this routing snapshot is kept — the snapshot may
        # predate the assignment that triggered the recovery, and the
        # write path already intersects _tracked with current replicas.
        live_nodes = set(state.nodes)
        with self._tracked_lock:
            for key in list(self._tracked):
                name, sid = key
                entry = routing[name][sid] if name in routing \
                    and sid < len(routing[name]) else None
                if entry is None:
                    if name not in indices:
                        self._tracked.pop(key, None)
                    continue
                keep = {t for t in self._tracked[key]
                        if t in live_nodes
                        and t not in entry.get("active_replicas", [])}
                if keep:
                    self._tracked[key] = keep
                else:
                    self._tracked.pop(key, None)
        # prune retention leases for departed copies: a dead node's lease
        # would pin the primary translog forever (the single-node path
        # removes its lease at recovery end; here the authoritative signal
        # is the node leaving the cluster or the copy leaving the routing)
        for (name, sid), shard in self.shards.items():
            if not shard.primary:
                continue
            entry = routing[name][sid] if name in routing \
                and sid < len(routing[name]) else None
            current = set(entry.get("replicas", [])) if entry else set()
            tracker = shard.engine.replication_tracker
            for lease_id in list(tracker.retention_leases):
                if not lease_id.startswith("peer_recovery/"):
                    continue
                target = lease_id[len("peer_recovery/"):]
                if target not in live_nodes or \
                        (entry is not None and target not in current):
                    tracker.remove_lease(lease_id)
        # create/adjust shards we own
        for name, shard_entries in routing.items():
            meta = indices.get(name)
            if meta is None:
                continue
            for sid, entry in enumerate(shard_entries):
                key = (name, sid)
                is_primary = entry.get("primary") == self.node_id
                is_replica = self.node_id in entry.get("replicas", [])
                if not (is_primary or is_replica):
                    continue
                shard = self.shards.get(key)
                if shard is not None and \
                        getattr(shard, "index_uuid", None) != meta.get("uuid"):
                    # same name, different index: the index was deleted and
                    # recreated between two applied states — the stale
                    # shard (old engine + old mappings) must not masquerade
                    # as the new index's shard (IndexMetadata UUID identity)
                    self.shards.pop(key, None)
                    shard.close()
                    with self._tracked_lock:
                        self._tracked.pop(key, None)
                    shard = None
                created_now = False
                if shard is None:
                    shard = self._create_shard(name, sid, meta, is_primary,
                                               entry)
                    if shard is None:
                        continue
                    self.shards[key] = shard
                    created_now = True
                if is_primary and not shard.primary:
                    # promotion (IndexShard relocated/promoted path):
                    # bump the primary term so replica-side op dedup sees
                    # the new reign
                    shard.primary = True
                    shard.engine.primary_term = entry.get("primary_term", 1)
                elif is_replica and shard.primary:
                    shard.primary = False
                if is_replica and \
                        self.node_id in entry.get("active_replicas", []):
                    self._started_pending.discard(key)
                if is_replica and not created_now and \
                        self.node_id not in entry.get("active_replicas",
                                                      []) and \
                        key not in self._started_pending and \
                        entry.get("primary") and \
                        entry["primary"] != self.node_id:
                    # listed as INITIALIZING but the shard already exists
                    # locally (e.g. a cancel + re-add to the same node in
                    # one fold, or a shard_failed round trip): re-recover —
                    # ops-based when the engine still has its state — and
                    # report started, or the copy sits initializing forever
                    key2 = (name, sid)
                    if key2 not in self._rerecovering:
                        self._rerecovering.add(key2)

                        def _rerun(shard=shard, name=name, sid=sid,
                                   primary=entry["primary"], key2=key2):
                            try:
                                self._recover_from(shard, name, sid,
                                                   primary)
                            except Exception:
                                # re-kick: without a fresh state update no
                                # reconcile would ever retry this copy
                                self.transport.scheduler.schedule_delayed(
                                    1000, self._kick_reconcile,
                                    "retry re-recovery")
                            finally:
                                self._rerecovering.discard(key2)
                        self.transport._workers.submit(_rerun)

    def _create_shard(self, name: str, sid: int, meta: dict,
                      is_primary: bool, entry: dict) -> Optional[IndexShard]:
        mapper = self._mapper_for(name, meta)
        # per-incarnation shard path keyed by index UUID so a deleted +
        # recreated index can never resurrect a stale store/translog
        shard_data_path = (os.path.join(self.data_path,
                                        meta.get("uuid") or name)
                           if self.data_path else None)
        shard = IndexShard(sid, mapper, index_name=name,
                           data_path=shard_data_path,
                           primary=is_primary,
                           primary_term=entry.get("primary_term", 1),
                           allocation_id=f"{name}_{sid}_{self.node_id}")
        shard.index_uuid = meta.get("uuid")
        if not is_primary:
            # replica: peer-recover from the primary over the transport
            primary_node = entry.get("primary")
            if primary_node and primary_node != self.node_id:
                try:
                    self._recover_from(shard, name, sid, primary_node)
                except Exception:
                    shard.close()
                    # backstop: without a re-kick the routing table keeps
                    # naming this node and nothing ever retries — the
                    # cluster would sit yellow forever (delayed-reroute
                    # retry, like the reference's RetryableAction around
                    # peer recovery)
                    self.transport.scheduler.schedule_delayed(
                        1000, self._kick_reconcile, "retry failed recovery")
                    return None
        return shard

    def _kick_reconcile(self):
        state = self.state
        if state is not None and self._started:
            self._on_state_applied(state)

    def _mapper_for(self, name: str, meta: dict) -> MapperService:
        # keyed by (name, index UUID): delete + recreate under the same
        # name is a DIFFERENT index (reference: IndexMetadata.getIndexUUID
        # identity), so the old mappings must not leak into the new one
        key = (name, meta.get("uuid"))
        mapper = self._mappers.get(key)
        if mapper is None:
            for stale in [k for k in self._mappers if k[0] == name]:
                del self._mappers[stale]
            mapper = MapperService(meta.get("mappings") or {})
            self._mappers[key] = mapper
        return mapper

    # ------------------------------------------------------------- recovery

    def _recover_from(self, shard: IndexShard, name: str, sid: int,
                      primary_node: str):
        """Peer recovery target side (PeerRecoveryTargetService): hand the
        primary our checkpoint; if a retention lease kept the ops we're
        missing, replay JUST those (sequence-number-based recovery), else
        pull the segment set in throttled chunks. Retries while the
        primary reports ShardNotReady — the replica's reconcile can apply
        the routing state before the primary's has created its shard."""
        resp = self._retry_shard_op(lambda: self.transport.send_sync(
            primary_node, START_RECOVERY,
            {"index": name, "shard": sid, "target": self.node_id,
             "local_checkpoint": shard.engine.local_checkpoint,
             "max_seq_no": shard.engine.max_seq_no},
            timeout=60.0))
        if resp["mode"] == "ops":
            term = resp["primary_term"]
            for op in _unwrap(resp["ops"]):
                if op.op_type == "index":
                    shard.index_on_replica(op.doc_id, op.source, op.seq_no,
                                           term, op.version)
                elif op.op_type == "delete":
                    shard.delete_on_replica(op.doc_id, op.seq_no, term,
                                            op.version)
                elif op.op_type == "noop":
                    # fill the seq-no gap or the local checkpoint stalls
                    # below max_seq_no forever (Engine.NoOp replay)
                    shard.engine.noop(op.seq_no, term,
                                      getattr(op, "reason", "") or
                                      "peer recovery replay")
            # finalize refresh (RecoveryTarget#finalizeRecovery): the copy
            # becomes an active search target, so replayed ops must be
            # visible before the leader marks it in-sync
            shard.refresh()
            RECOVERY_STATS["ops"] += 1
        else:
            # file phase: pull each segment in rate-limited chunks
            # (RecoverySourceHandler.phase1 + RateLimiter on
            # indices.recovery.max_bytes_per_sec), reassemble, install
            session = resp["session"]
            blobs = []
            for seg_id, nbytes in resp["manifest"]:
                buf = bytearray()
                while len(buf) < nbytes:
                    chunk = self.transport.send_sync(
                        primary_node, RECOVERY_CHUNK,
                        {"index": name, "shard": sid, "session": session,
                         "seg_id": seg_id, "offset": len(buf)},
                        timeout=60.0)
                    data = np.asarray(_unwrap(chunk["data"]),
                                      dtype=np.uint8)
                    if not len(data):
                        raise OpenSearchTpuError(
                            f"recovery chunk underrun for [{seg_id}]")
                    buf.extend(data.tobytes())
                blobs.append(bytes(buf))
            from opensearch_tpu.transport import serde
            segments = [serde.safe_pickle_loads(b) for b in blobs]
            shard.engine.install_segments(
                segments, max_seq_no=resp["max_seq_no"],
                local_checkpoint=resp["local_checkpoint"])
            shard._sync_reader()
            RECOVERY_STATS["file"] += 1
        self.transport.send_sync(
            primary_node, RECOVERY_DONE,
            {"index": name, "shard": sid, "target": self.node_id,
             "local_checkpoint": shard.engine.local_checkpoint},
            timeout=30.0)
        self._started_pending.add((name, sid))
        self._submit_to_leader({"kind": "shard_started", "index": name,
                                "shard": sid, "node": self.node_id})

    def _recovery_rate_limit(self) -> float:
        """indices.recovery.max_bytes_per_sec (default 40mb) as bytes/s."""
        from opensearch_tpu.common.settings import parse_byte_size
        key = "indices.recovery.max_bytes_per_sec"
        for scope in ("transient", "persistent"):
            v = self.local.cluster_settings.get(scope, {}).get(key)
            if v is not None:
                return parse_byte_size(v, key)
        return parse_byte_size("40mb", key)

    def _on_start_recovery(self, sender: str, payload: dict):
        """Source side (RecoverySourceHandler.recoverToTarget): register
        the target for op tracking FIRST (ops that arrive while the copy
        is in flight still reach it), pin a retention lease at the
        target's checkpoint, then answer with ops (lease held the history)
        or a chunked-segment manifest."""
        key = (payload["index"], payload["shard"])
        shard = self.shards.get(key)
        if shard is None or not shard.primary:
            # retryable: the target may be recovering before this node's
            # own reconcile created the primary shard
            raise ShardNotReadyError(
                f"not primary for [{key}] on [{self.node_id}]")
        target = payload["target"]
        with self._tracked_lock:
            self._tracked.setdefault(key, set()).add(target)
        engine = shard.engine
        target_ckpt = int(payload.get("local_checkpoint", -1))
        tracker = engine.replication_tracker
        tracker.add_lease(f"peer_recovery/{target}", target_ckpt + 1,
                          "peer recovery")
        # ops-based fast path: every op in (target_ckpt, max_seq_no] must
        # still be in the translog (the lease prevents future trims; a
        # PAST trim may already have dropped them)
        ops = (engine.translog.read_ops(from_seq_no=target_ckpt + 1)
               if engine.translog is not None and target_ckpt >= 0 else None)
        if ops is not None:
            expected = set(range(target_ckpt + 1, engine.max_seq_no + 1))
            if expected <= {o.seq_no for o in ops}:
                return {"mode": "ops", "ops": Opaque(ops),
                        "primary_term": engine.primary_term}
        engine.refresh()
        from opensearch_tpu.transport import serde
        # expire sessions abandoned by crashed targets (their blobs hold a
        # full serialized copy of the shard)
        now = time.monotonic()
        for stale in [sid for sid, sess in self._recovery_sessions.items()
                      if now - sess["ts"] > 900.0]:
            del self._recovery_sessions[stale]
        # key the session by (target, index, shard): finalize of one
        # shard's recovery must not destroy the blobs of another shard
        # concurrently recovering from this source to the same target
        # (allowed by node_concurrent_recoveries)
        session = (f"{target}/{payload['index']}/{payload['shard']}"
                   f"/{time.monotonic_ns()}")
        # raw restricted-codec bytes: chunks travel as uint8 arrays (one
        # base64 layer at the frame, zlib-compressed) instead of
        # double-encoding pickle-in-json-in-pickle
        blobs = {s.seg_id: serde.safe_pickle_dumps(s)
                 for s in engine.segments}
        self._recovery_sessions[session] = {
            "blobs": blobs, "ts": now}
        return {"mode": "segments", "session": session,
                "manifest": [(s.seg_id, len(blobs[s.seg_id]))
                             for s in engine.segments],
                "max_seq_no": engine.max_seq_no,
                "local_checkpoint": engine.local_checkpoint}

    def _on_recovery_chunk(self, sender: str, payload: dict):
        """One rate-limited chunk of a segment blob (RecoverySourceHandler
        sends file chunks through a RateLimiter)."""
        session = self._recovery_sessions.get(payload["session"])
        if session is None:
            raise OpenSearchTpuError(
                f"unknown recovery session [{payload['session']}]")
        blob = session["blobs"].get(payload["seg_id"])
        if blob is None:
            raise OpenSearchTpuError(
                f"unknown segment [{payload['seg_id']}] in session")
        offset = int(payload["offset"])
        chunk = blob[offset:offset + RECOVERY_CHUNK_BYTES]
        # source-side throttle: sleep long enough that this chunk fits the
        # configured bandwidth budget
        rate = self._recovery_rate_limit()
        if rate > 0 and chunk:
            time.sleep(len(chunk) / rate)
        return {"data": np.frombuffer(chunk, dtype=np.uint8)}

    def _on_recovery_done(self, sender: str, payload: dict):
        """Finalize (RecoverySourceHandler.finalizeRecovery): renew the
        target's lease at its post-recovery checkpoint — future
        re-recoveries of this copy can then be ops-based — and drop the
        session blobs."""
        key = (payload["index"], payload["shard"])
        shard = self.shards.get(key)
        target = payload["target"]
        if shard is not None and shard.primary:
            # add-or-renew: a concurrent reroute may have pruned the
            # recovery lease mid-flight; finalize must not fail a recovery
            # that already installed its copy
            tracker = shard.engine.replication_tracker
            lease_id = f"peer_recovery/{target}"
            ckpt = int(payload.get("local_checkpoint", -1)) + 1
            if lease_id in tracker.retention_leases:
                tracker.renew_lease(lease_id, ckpt)
            else:
                tracker.add_lease(lease_id, ckpt, "peer recovery")
        prefix = f"{target}/{payload['index']}/{payload['shard']}/"
        for sid_key in [s for s in self._recovery_sessions
                        if s.startswith(prefix)]:
            del self._recovery_sessions[sid_key]
        return {"ok": True}

    # ------------------------------------------------------- write path

    def _register_actions(self):
        t = self.transport
        reg = t.register_handler
        # management pool: a leader update blocks until publication commit
        # (up to ~80s) — it must never occupy a data-plane worker slot
        reg(self.node_id, LEADER_UPDATE,
            lambda s, p: {"accepted": self._leader_apply_update(p)},
            blocking=True, pool="management")
        # fan-out handlers (a primary waits on replica sub-requests, CCS
        # waits on shard queries) run on the generic pool, NOT the pool
        # their leaf sub-requests execute on — sharing one bounded pool
        # between waiters and waited-on is a distributed deadlock once
        # pool-size blockers are in flight on both sides
        reg(self.node_id, SHARD_BULK_PRIMARY, self._on_shard_bulk_primary,
            blocking=True, pool="generic")
        reg(self.node_id, SHARD_BULK_REPLICA, self._on_shard_bulk_replica,
            blocking=True)
        reg(self.node_id, SHARD_QUERY, self._on_shard_query, blocking=True,
            pool="search")
        reg(self.node_id, SHARD_DFS, self._on_shard_dfs, blocking=True,
            pool="search")
        reg(self.node_id, SHARD_FETCH, self._on_shard_fetch, blocking=True,
            pool="search")
        reg(self.node_id, SHARD_GET, self._on_shard_get, blocking=True,
            pool="get")
        reg(self.node_id, SHARD_REFRESH, self._on_shard_refresh,
            blocking=True)
        reg(self.node_id, START_RECOVERY, self._on_start_recovery,
            blocking=True, pool="management")
        reg(self.node_id, RECOVERY_CHUNK, self._on_recovery_chunk,
            blocking=True, pool="management")
        reg(self.node_id, RECOVERY_DONE, self._on_recovery_done,
            blocking=True, pool="management")
        reg(self.node_id, REGISTER_ADDR, self._on_register_address,
            blocking=True, pool="management")
        reg(self.node_id, CCS_QUERY, self._on_ccs_query, blocking=True,
            pool="generic")
        reg(self.node_id, CCS_FETCH, self._on_ccs_fetch, blocking=True,
            pool="generic")

    def _on_register_address(self, sender: str, payload: dict):
        """Learn a joining node's transport address; propagate to the
        leader so it lands in cluster state for every member."""
        self.transport.add_address(payload["node"], *payload["addr"])
        if self.is_leader:
            self._leader_apply_update({"kind": "register_address",
                                       "node": payload["node"],
                                       "addr": payload["addr"],
                                       "attrs": payload.get("attrs")})
        else:
            leader = self._leader_id()
            if leader and leader != payload["node"]:
                try:
                    self.transport.send_sync(leader, REGISTER_ADDR, payload,
                                             timeout=10.0)
                except OpenSearchTpuError:
                    pass
        addresses = {nid: list(a)
                     for nid, a in self.transport._addresses.items()}
        addresses[self.node_id] = list(self.address)
        return {"ok": True, "addresses": addresses}

    def _on_shard_bulk_primary(self, sender: str, payload: dict) -> dict:
        """TransportShardBulkAction.performOnPrimary: execute each op on
        the local primary, then fan the seqno'd ops to every in-sync +
        tracked replica copy concurrently (ReplicationOperation.java:221)."""
        name, sid = payload["index"], payload["shard"]
        key = (name, sid)
        shard = self.shards.get(key)
        if shard is None or not shard.primary:
            raise ShardNotReadyError(
                f"shard [{name}][{sid}] not primary on [{self.node_id}]")
        entry = self._routing_entry(name, sid)
        results = []
        replica_ops = []
        for op in payload["ops"]:
            try:
                if op["op"] == "delete":
                    res = shard.delete_doc(op["id"])
                    result = "deleted" if res.found else "not_found"
                else:
                    res = shard.index_doc(op["id"], op["source"],
                                          op_type=op.get("op_type", "index"))
                    result = "created" if res.created else "updated"
                results.append({"id": op["id"], "result": result,
                                "_version": res.version,
                                "_seq_no": res.seq_no,
                                "_primary_term": shard.engine.primary_term,
                                "status": 201 if result == "created"
                                else 200})
                replica_ops.append({**op, "seq_no": res.seq_no,
                                    "version": res.version})
            except OpenSearchTpuError as e:
                results.append({"id": op["id"], "error": str(e),
                                "status": e.status})
        # replicate to in-sync + tracked copies
        with self._tracked_lock:
            tracked = set(self._tracked.get(key, set()))
        targets = set(entry.get("active_replicas", [])) | tracked
        targets &= set(entry.get("replicas", []))
        failures = []
        threads = []
        for target in sorted(targets):
            def run(tgt=target):
                try:
                    self.transport.send_sync(
                        tgt, SHARD_BULK_REPLICA,
                        {"index": name, "shard": sid,
                         "primary_term": shard.engine.primary_term,
                         "ops": replica_ops}, timeout=30.0)
                except Exception as e:
                    failures.append((tgt, e))
            th = threading.Thread(target=run, daemon=True)
            th.start()
            threads.append(th)
        for th in threads:
            th.join(35.0)
        # a failed replica is reported to the leader so it can be failed
        # out of the in-sync set (ReplicationOperation#onNoLongerPrimary /
        # failShardIfNeeded analog)
        for tgt, _ in failures:
            try:
                self._submit_to_leader({"kind": "shard_failed",
                                        "index": name, "shard": sid,
                                        "node": tgt})
            except OpenSearchTpuError:
                pass
        return {"items": results}

    def _on_shard_bulk_replica(self, sender: str, payload: dict) -> dict:
        key = (payload["index"], payload["shard"])
        shard = self.shards.get(key)
        if shard is None:
            raise OpenSearchTpuError(f"no shard [{key}] on [{self.node_id}]")
        term = payload["primary_term"]
        for op in payload["ops"]:
            if op["op"] == "delete":
                shard.delete_on_replica(op["id"], op["seq_no"], term,
                                        op["version"])
            else:
                shard.index_on_replica(op["id"], op["source"], op["seq_no"],
                                       term, op["version"])
        return {"ok": True}

    def _routing_entry(self, name: str, sid: int) -> dict:
        routing = self._data().get("routing", {})
        shards = routing.get(name)
        if shards is None or sid >= len(shards):
            raise IndexNotFoundError(f"no such index [{name}]")
        return shards[sid]

    def _index_meta(self, name: str) -> dict:
        meta = self._data().get("indices", {}).get(name)
        if meta is None:
            raise IndexNotFoundError(f"no such index [{name}]")
        return meta

    def _num_shards(self, name: str) -> int:
        return len(self._data().get("routing", {}).get(name) or []) or 1

    def _shard_for_doc(self, name: str, doc_id: str,
                       routing: Optional[str] = None) -> int:
        meta = self._index_meta(name)
        settings = meta.get("settings", {})
        num_shards = int(settings.get("number_of_shards", 1))
        return generate_shard_id(
            doc_id, num_shards, routing=routing,
            # shrink/split keep the ORIGINAL routing space; partitioned
            # indices spread one routing value over several shards — both
            # must match the local IndexService's routing exactly or a
            # cluster write lands on a different shard than a local one
            routing_num_shards=int(settings.get(
                "number_of_routing_shards", num_shards)),
            routing_partition_size=int(settings.get(
                "routing_partition_size", 1)))

    def _retry_shard_op(self, attempt, timeout: float = 10.0):
        """Run a shard-level operation, retrying while the target reports
        ShardNotReadyError — the window where routing has been published
        but the owning node hasn't finished creating/tearing down the
        shard. The reference retries these through a ClusterStateObserver
        (TransportReplicationAction retryPrimaryException); `attempt`
        re-resolves routing on every call so a moved shard is found."""
        deadline = time.time() + timeout
        while True:
            try:
                return attempt()
            except (ShardNotReadyError, RemoteTransportError) as e:
                retryable = isinstance(e, ShardNotReadyError) or \
                    e.error_type == ShardNotReadyError.error_type
                if not retryable or time.time() >= deadline:
                    raise
                time.sleep(0.1)

    def execute_bulk(self, ops_by_index: List[dict]) -> dict:
        """Group ops per shard, dispatch per-shard bulks to primaries
        (local or remote), reassemble per-item results in order."""
        groups: Dict[Tuple[str, int], List[Tuple[int, dict]]] = {}
        for i, op in enumerate(ops_by_index):
            sid = self._shard_for_doc(op["index"], op["id"],
                                      op.get("routing"))
            groups.setdefault((op["index"], sid), []).append((i, op))
        items: List[Optional[dict]] = [None] * len(ops_by_index)
        errors = False
        for (name, sid), group in groups.items():
            payload = {"index": name, "shard": sid,
                       "ops": [op for _, op in group]}

            def dispatch(name=name, sid=sid, payload=payload):
                entry = self._routing_entry(name, sid)
                primary = entry.get("primary")
                if primary is None:
                    raise ShardNotReadyError("primary shard not active")
                if primary == self.node_id:
                    return entry, self._on_shard_bulk_primary(
                        self.node_id, payload)
                return entry, self.transport.send_sync(
                    primary, SHARD_BULK_PRIMARY, payload, timeout=60.0)

            try:
                entry, resp = self._retry_shard_op(dispatch)
            except OpenSearchTpuError as e:
                try:
                    entry = self._routing_entry(name, sid)
                except OpenSearchTpuError:
                    # e.g. the index was deleted mid-bulk: still report
                    # per-item errors rather than failing the whole bulk
                    entry = {"replicas": [], "active_replicas": []}
                resp = {"items": [{"id": op["id"], "status": e.status,
                                   "error": str(e) or e.error_type}
                                  for _, op in group]}
            for (i, op), item in zip(group, resp["items"]):
                action = "delete" if op["op"] == "delete" else "index"
                body = {"_index": name, "_id": item["id"],
                        "status": item.get("status", 200)}
                if "error" in item:
                    errors = True
                    body["error"] = {"type": "exception",
                                     "reason": item["error"]}
                else:
                    body.update({"result": item["result"],
                                 "_version": item["_version"],
                                 "_seq_no": item["_seq_no"],
                                 "_primary_term": item["_primary_term"],
                                 "_shards": {"total": 1 + len(
                                     entry.get("replicas", [])),
                                     "successful": 1 + len(
                                     entry.get("active_replicas", [])),
                                     "failed": 0}})
                items[i] = {action: body}
        return {"took": 0, "errors": errors, "items": items}

    # ------------------------------------------------------------ read path

    def _on_shard_get(self, sender: str, payload: dict):
        shard = self.shards.get((payload["index"], payload["shard"]))
        if shard is None:
            raise ShardNotReadyError("shard not local")
        res = shard.get_doc(payload["id"])
        if res is None:
            return {"found": False}
        return {"found": True, "source": res.source, "version": res.version,
                "seq_no": res.seq_no, "primary_term": res.primary_term}

    def get_doc(self, name: str, doc_id: str,
                routing: Optional[str] = None) -> dict:
        sid = self._shard_for_doc(name, doc_id, routing)
        payload = {"index": name, "shard": sid, "id": doc_id}

        def dispatch():
            entry = self._routing_entry(name, sid)
            primary = entry.get("primary")
            if primary is None:
                raise ShardNotReadyError("primary shard not active")
            if primary == self.node_id:
                return self._on_shard_get(self.node_id, payload)
            return self.transport.send_sync(primary, SHARD_GET, payload,
                                            timeout=30.0)

        resp = self._retry_shard_op(dispatch)
        out = {"_index": name, "_id": doc_id, "found": resp["found"]}
        if resp["found"]:
            out.update({"_source": resp["source"],
                        "_version": resp["version"],
                        "_seq_no": resp["seq_no"],
                        "_primary_term": resp["primary_term"]})
        return out

    def _on_shard_refresh(self, sender: str, payload: dict):
        for sid in payload["shards"]:
            shard = self.shards.get((payload["index"], sid))
            if shard is not None:
                shard.refresh()
        return {"ok": True}

    def refresh_index(self, name: str) -> dict:
        by_node: Dict[str, List[int]] = {}
        for sid, entry in enumerate(self._data()["routing"].get(name, [])):
            for node in shard_copies(entry):
                by_node.setdefault(node, []).append(sid)
        total = 0
        for node, sids in by_node.items():
            payload = {"index": name, "shards": sids}
            if node == self.node_id:
                self._on_shard_refresh(self.node_id, payload)
            else:
                self.transport.send_sync(node, SHARD_REFRESH, payload,
                                         timeout=30.0)
            total += len(sids)
        return {"_shards": {"total": total, "successful": total,
                            "failed": 0}}

    # ---------------------------------------------------------- search path

    def _on_shard_query(self, sender: str, payload: dict):
        """Shard-side query phase: run the local jitted pipeline, return
        candidates + decoded agg partials (SearchService.executeQueryPhase
        → QuerySearchResult)."""
        name = payload["index"]
        body = payload["body"]
        k = payload["k"]
        from opensearch_tpu.search.canmatch import shard_can_match
        shards = {}
        for sid in payload["shards"]:
            shard = self.shards.get((name, sid))
            if shard is None:
                raise ShardNotReadyError(f"shard [{name}][{sid}] not local")
            shards[sid] = shard
        # data-node-side can-match (SearchService#canMatch): a provably
        # empty shard skips plan compilation and the device launch. If an
        # aggs request would skip ALL local shards, one still executes so
        # the reduce gets properly-shaped empty agg partials.
        skip = {sid for sid, sh in shards.items()
                if not shard_can_match(sh.executor, body)}
        if (body.get("aggs") or body.get("aggregations")) \
                and skip == set(shards):
            skip.discard(min(skip))
        # DFS-pinned global stats (dfs_query_then_fetch): the coordinator
        # merged every shard's term statistics into body["_dfs"]
        dfs = body.get("_dfs")
        out = []
        for sid, shard in shards.items():
            if sid in skip:
                out.append({"shard": sid, "candidates": Opaque([]),
                            "partials": Opaque([]), "total": 0,
                            "skipped": True})
                continue
            override = None
            if dfs:
                from opensearch_tpu.search.compile import StaticStats
                override = StaticStats(
                    shard.executor.reader.stats(),
                    {f: tuple(v) for f, v in dfs["fields"].items()},
                    dfs["term_df"])
            cands, decoded, total = shard.executor.execute_query_phase(
                body, k, stats_override=override)
            out.append({"shard": sid,
                        "candidates": Opaque(
                            [(c.score, c.seg_i, c.ord, c.sort_values)
                             for c in cands]),
                        "partials": Opaque(decoded),
                        "total": total})
        return {"results": out}

    def _on_shard_dfs(self, sender: str, payload: dict):
        """Shard-side DFS phase (DfsPhase.execute): report this node's
        term/field statistics for the query so the coordinator can merge
        them (dfs_query_then_fetch)."""
        from opensearch_tpu.search import dsl
        from opensearch_tpu.search.compile import (collect_query_term_stats,
                                                   merge_dfs_stats)
        name = payload["index"]
        parts = []
        for sid in payload["shards"]:
            shard = self.shards.get((name, sid))
            if shard is None:
                raise ShardNotReadyError(f"shard [{name}][{sid}] not local")
            reader = shard.executor.reader
            node = dsl.parse_query(payload["body"].get("query"))
            parts.append(collect_query_term_stats(node, reader.mapper,
                                                  reader.stats()))
        fields, term_df = merge_dfs_stats(parts)
        return {"fields": {f: list(v) for f, v in fields.items()},
                "term_df": term_df}

    def _dfs_prephase(self, name: str, body: dict) -> dict:
        """Coordinator half: fan SHARD_DFS to one copy of every shard (in
        parallel, with the same routing re-resolution retry the query
        phase uses), merge (SearchPhaseController#aggregateDfs), and
        return the body with the merged stats pinned under `_dfs`."""
        from opensearch_tpu.search.compile import merge_dfs_stats
        deadline = time.time() + 10.0
        while True:
            routing = self._data().get("routing", {})
            if name not in routing:
                raise IndexNotFoundError(f"no such index [{name}]")
            by_node: Dict[str, List[int]] = {}
            unassigned = None
            for sid, entry in enumerate(routing[name]):
                copies = ([entry["primary"]] if entry.get("primary")
                          else []) + list(entry.get("active_replicas", []))
                if not copies:
                    unassigned = sid
                    break
                by_node.setdefault(copies[0], []).append(sid)
            if unassigned is not None:
                if time.time() >= deadline:
                    raise ShardNotReadyError(
                        f"no active copy for shard [{name}][{unassigned}]")
                time.sleep(0.1)
                continue
            parts: List = []
            errors: List[Exception] = []
            lock = threading.Lock()

            def dfs_node(node: str, sids: List[int]):
                payload = {"index": name, "shards": sids, "body": body}
                try:
                    if node == self.node_id:
                        resp = self._on_shard_dfs(self.node_id, payload)
                    else:
                        resp = self.transport.send_sync(
                            node, SHARD_DFS, payload, timeout=30.0)
                    with lock:
                        parts.append((
                            {f: tuple(v)
                             for f, v in resp["fields"].items()},
                            resp["term_df"]))
                except Exception as e:
                    errors.append(e)

            threads = [threading.Thread(target=dfs_node, args=(n, s),
                                        daemon=True)
                       for n, s in by_node.items()]
            for th in threads:
                th.start()
            for th in threads:
                th.join(35.0)
            if not errors:
                break
            retryable = all(
                isinstance(e, ShardNotReadyError)
                or (isinstance(e, RemoteTransportError)
                    and e.error_type == ShardNotReadyError.error_type)
                for e in errors)
            if not retryable or time.time() >= deadline:
                raise errors[0]
            time.sleep(0.1)
        fields, term_df = merge_dfs_stats(parts)
        return {**body, "_dfs": {"fields": {f: list(v)
                                            for f, v in fields.items()},
                                 "term_df": term_df}}

    def _on_shard_fetch(self, sender: str, payload: dict):
        """Shard-side fetch phase: render hit dicts for the winning docs
        (SearchService.executeFetchPhase → FetchPhase.execute)."""
        from opensearch_tpu.search import dsl
        from opensearch_tpu.search.controller import (
            _build_hit, _parse_sort)
        from opensearch_tpu.search.executor import _Candidate

        name, sid = payload["index"], payload["shard"]
        body = payload["body"]
        shard = self.shards.get((name, sid))
        if shard is None:
            raise ShardNotReadyError(f"shard [{name}][{sid}] not local")
        sort_specs = _parse_sort(body.get("sort"))
        score_sorted = sort_specs[0][0] == "_score"
        query_node = dsl.parse_query(body.get("query"))
        wants_score = score_sorted or bool(body.get("track_scores"))
        # page-scoped inner-hits context: spec collection and the child
        # evaluation cache amortize over this fetch page, same as the
        # single-node controller
        from opensearch_tpu.search import fetch as fetch_phase
        inner_specs = fetch_phase.collect_inner_hit_specs(query_node)
        inner_cache: dict = {}
        hits = []
        for score, seg_i, ord_, sort_values in payload["docs"]:
            c = _Candidate(score, seg_i, ord_, sort_values)
            hit = _build_hit(shard.executor, c, body,
                             score if wants_score else None,
                             query_node, sort_specs, score_sorted,
                             inner_specs=inner_specs,
                             inner_cache=inner_cache)
            hits.append(hit)
        return {"hits": Opaque(hits)}

    def _select_copy(self, copies: List[str]) -> str:
        """Adaptive replica selection (OperationRouting.java:339): rank
        each copy by (outstanding+1) * service-time EWMA and take the
        minimum; a round-robin starting offset spreads load while stats
        are cold/equal. Failed copies never appear — routing drops them
        from active_replicas before selection."""
        if len(copies) == 1:
            return copies[0]
        with self._ars_lock:
            self._ars_rr += 1
            start = self._ars_rr % len(copies)
            ordered = copies[start:] + copies[:start]
            best, best_rank = ordered[0], None
            for n in ordered:
                ewma, outstanding = self._ars.setdefault(n, [10.0, 0])
                rank = (outstanding + 1.0) * ewma
                if best_rank is None or rank < best_rank:
                    best, best_rank = n, rank
            # decay non-winners so a copy that was never (or long ago)
            # sampled drifts back into rotation instead of being starved
            # by one fast measurement (ResponseCollectorService's
            # adjustment of unselected nodes)
            for n in ordered:
                if n != best:
                    self._ars[n][0] *= 0.95
        return best

    def _ars_begin(self, node: str) -> None:
        """Mark a query-phase request outstanding against [node]."""
        with self._ars_lock:
            st = self._ars.setdefault(node, [10.0, 0])
            st[1] += 1

    def _ars_end(self, node: str, took_ms: float) -> None:
        """Fold one measured service time into [node]'s EWMA. A seam so
        tests can inject deterministic timings instead of observing
        wall-clock-dependent rotation."""
        with self._ars_lock:
            st = self._ars.setdefault(node, [10.0, 0])
            st[0] = 0.7 * st[0] + 0.3 * took_ms
            st[1] = max(0, st[1] - 1)

    def _cluster_query_phase(self, name: str, body: dict, k: int):
        """Scatter the query phase over one copy of every shard of a local
        index; returns (candidates, agg partials, total hits, shard→node
        map for the fetch phase, shard count)."""
        from opensearch_tpu.search.executor import _Candidate

        # scatter with routing re-resolution: a shard may move or finish
        # initializing between attempts (the ClusterStateObserver-style
        # retry — re-grouping by node each round, unlike a node-pinned
        # retry which would hammer a stale owner)
        deadline = time.time() + 10.0
        while True:
            routing = self._data().get("routing", {})
            if name not in routing:
                raise IndexNotFoundError(f"no such index [{name}]")
            # pick one copy per shard: primary preferred (fully
            # consistent), else an in-sync replica (ARS slots in here)
            by_node: Dict[str, List[int]] = {}
            # the fetch phase must hit the same copy that served the query
            # phase (candidates carry that node's segment indices)
            shard_nodes: Dict[int, str] = {}
            unassigned = None
            for sid, entry in enumerate(routing[name]):
                copies = []
                p = entry.get("primary")
                if p is not None:
                    copies.append(p)
                copies += [n for n in entry.get("active_replicas", [])
                           if n != p]
                if not copies:
                    unassigned = sid
                    break
                node = self._select_copy(copies)
                by_node.setdefault(node, []).append(sid)
                shard_nodes[sid] = node
            if unassigned is not None:
                # transient failover/creation window: retry like the
                # per-node ShardNotReadyError path below
                if time.time() >= deadline:
                    raise ShardNotReadyError(
                        f"no active copy for shard [{name}][{unassigned}]")
                time.sleep(0.1)
                continue

            # query phase per node (parallel)
            all_candidates: List[_Candidate] = []
            all_partials = []
            total = 0
            skipped = 0
            lock = threading.Lock()
            errors: List[Exception] = []

            def query_node_shards(node: str, sids: List[int]):
                nonlocal total, skipped
                payload = {"index": name, "shards": sids, "body": body,
                           "k": k}
                t0 = time.monotonic()
                self._ars_begin(node)
                try:
                    if node == self.node_id:
                        resp = self._on_shard_query(self.node_id, payload)
                    else:
                        resp = self.transport.send_sync(
                            node, SHARD_QUERY, payload, timeout=60.0)
                    with lock:
                        for res in resp["results"]:
                            for score, seg_i, ord_, sv in _unwrap(
                                    res["candidates"]):
                                c = _Candidate(score, seg_i, ord_, sv,
                                               shard_i=res["shard"])
                                all_candidates.append(c)
                            all_partials.extend(_unwrap(res["partials"]))
                            total += res["total"]
                            if res.get("skipped"):
                                skipped += 1
                except Exception as e:
                    errors.append(e)
                finally:
                    self._ars_end(node, (time.monotonic() - t0) * 1000.0)

            threads = [threading.Thread(target=query_node_shards,
                                        args=(node, sids), daemon=True)
                       for node, sids in by_node.items()]
            for th in threads:
                th.start()
            for th in threads:
                th.join(65.0)
            if not errors:
                break

            def _retryable(e: Exception) -> bool:
                return isinstance(e, ShardNotReadyError) or (
                    isinstance(e, RemoteTransportError)
                    and e.error_type == ShardNotReadyError.error_type)

            hard = [e for e in errors if not _retryable(e)]
            if hard or time.time() >= deadline:
                raise (hard or errors)[0]
            time.sleep(0.1)

        return (all_candidates, all_partials, total, shard_nodes,
                len(routing[name]), skipped)

    def _cluster_fetch(self, name: str, body: dict, page: List,
                       shard_nodes: Dict[int, str]) -> Dict[Tuple, dict]:
        """Fetch phase: render hit dicts for the winning docs from the
        copies that served them. Returns (shard, seg, ord) → hit."""
        docs_by_shard: Dict[int, List] = {}
        for c in page:
            docs_by_shard.setdefault(c.shard_i, []).append(c)
        hit_map: Dict[Tuple[int, int, int], dict] = {}
        for sid, cands in docs_by_shard.items():
            node = shard_nodes[sid]
            payload = {"index": name, "shard": sid, "body": body,
                       "docs": [(c.score, c.seg_i, c.ord, c.sort_values)
                                for c in cands]}
            if node == self.node_id:
                resp = self._on_shard_fetch(self.node_id, payload)
            else:
                resp = self.transport.send_sync(node, SHARD_FETCH, payload,
                                                timeout=60.0)
            for c, hit in zip(cands, _unwrap(resp["hits"])):
                hit_map[(c.shard_i, c.seg_i, c.ord)] = hit
        return hit_map

    def search(self, name: str, body: Optional[dict]) -> dict:
        """Coordinator side of query-then-fetch over the transport."""
        from opensearch_tpu.search.aggs.parse import parse_aggs
        from opensearch_tpu.search.aggs.pipeline import apply_pipelines
        from opensearch_tpu.search.aggs.reduce import reduce_aggs
        from opensearch_tpu.search.controller import (
            _compare_candidates, _parse_sort)

        body = body or {}
        start = time.monotonic()
        size = int(body.get("size", 10))
        from_ = int(body.get("from", 0))
        sort_specs = _parse_sort(body.get("sort"))
        score_sorted = sort_specs[0][0] == "_score"
        wants_score = score_sorted or bool(body.get("track_scores"))
        k = max(from_ + size, 10)

        if body.get("search_type") == "dfs_query_then_fetch":
            body = self._dfs_prephase(name, body)

        (all_candidates, all_partials, total, shard_nodes,
         n_shards, skipped) = self._cluster_query_phase(name, body, k)

        # coordinator reduce: global sort + page (SearchPhaseController)
        all_candidates.sort(key=_compare_candidates(sort_specs))
        page = all_candidates[from_:from_ + size]
        max_score = None
        if wants_score:
            for c in all_candidates:
                if max_score is None or c.score > max_score:
                    max_score = c.score

        # fetch phase: only shards owning page hits (FetchSearchPhase)
        hit_map = self._cluster_fetch(name, body, page, shard_nodes)
        hits = [hit_map[(c.shard_i, c.seg_i, c.ord)] for c in page]

        resp: dict = {
            "took": int((time.monotonic() - start) * 1000),
            "timed_out": False,
            "_shards": {"total": n_shards, "successful": n_shards,
                        "skipped": skipped, "failed": 0},
            "hits": {"total": {"value": total, "relation": "eq"},
                     "max_score": max_score, "hits": hits},
        }
        agg_nodes = parse_aggs(body.get("aggs") or body.get("aggregations"))
        if agg_nodes:
            aggregations = reduce_aggs(all_partials)
            apply_pipelines(agg_nodes, aggregations)
            resp["aggregations"] = aggregations
        return resp

    # ------------------------------------------------- cross-cluster search

    def register_remote(self, alias: str, host: str, port: int):
        """Register a remote cluster via one seed address (the sniff
        strategy's seed list, SniffConnectionStrategy; one seed suffices
        because the remote coordinator fans out internally)."""
        key = f"remote:{alias}"
        self.transport.add_address(key, host, port)
        self._remotes[alias] = key

    def remove_remote(self, alias: str):
        self._remotes.pop(alias, None)

    def allocation_explain(self, body: Optional[dict] = None) -> dict:
        """_cluster/allocation/explain (ClusterAllocationExplainAction):
        run the decider chain for one shard against every live node and
        report each decider's verdict — the operator's why-is-this-shard-
        where-it-is (or unassigned) tool."""
        from opensearch_tpu.cluster.deciders import (AllocationContext,
                                                     can_allocate)
        body = body or {}
        data = self._data()
        routing = data.get("routing", {})
        live = sorted(self.state.nodes) if self.state else []
        index = body.get("index")
        shard = body.get("shard")
        want_primary = body.get("primary")
        if index is None:
            # no target given: explain the first unassigned copy, like the
            # reference's findShardToExplain
            for name, shards in routing.items():
                for sid, e in enumerate(shards):
                    if e.get("primary") is None:
                        index, shard, want_primary = name, sid, True
                        break
                    settings = (data.get("indices", {}).get(name) or {}) \
                        .get("settings", {})
                    if len(e.get("replicas", [])) < int(
                            settings.get("number_of_replicas", 0)):
                        index, shard, want_primary = name, sid, False
                        break
                if index is not None:
                    break
            if index is None:
                raise IllegalArgumentError(
                    "unable to find any unassigned shards to explain")
        try:
            shard = int(shard or 0)
        except (TypeError, ValueError):
            raise IllegalArgumentError(
                f"[shard] must be an integer, got [{shard}]")
        if index not in routing or not 0 <= shard < len(routing[index]):
            raise IndexNotFoundError(f"no such shard [{index}][{shard}]")
        entry = routing[index][shard]
        want_primary = bool(want_primary if want_primary is not None
                            else True)
        ctx = AllocationContext(data, live)
        decisions = []
        for n in live:
            d = can_allocate(ctx, index, entry, n, is_primary=want_primary)
            row = {"node_id": n, "node_name": n,
                   "node_decision": d.kind.lower(),
                   "node_attributes": (data.get("node_attrs") or {})
                   .get(n, {})}
            if d.kind != "YES":
                row["deciders"] = [{"decider": d.decider,
                                    "decision": d.kind,
                                    "explanation": d.reason}]
            decisions.append(row)
        assigned = entry.get("primary") if want_primary else None
        if want_primary:
            copy_started = entry.get("primary") is not None
        else:
            # a replica copy is only "started" when the DESIRED count is
            # met — some replicas existing doesn't mean the one being
            # explained is assigned
            desired = int(((data.get("indices", {}).get(index) or {})
                           .get("settings") or {})
                          .get("number_of_replicas", 0))
            copy_started = len(entry.get("replicas", [])) >= desired
        out = {
            "index": index, "shard": shard, "primary": want_primary,
            "current_state": "started" if copy_started else "unassigned",
            "can_allocate": (
                "yes" if any(r["node_decision"] == "yes"
                             for r in decisions)
                else "throttled" if any(r["node_decision"] == "throttle"
                                        for r in decisions)
                else "no"),
            "node_allocation_decisions": decisions,
        }
        if assigned:
            out["current_node"] = {"id": assigned, "name": assigned}
        return out

    # ------------------------------------------------------ persistent tasks

    def start_persistent_task(self, task_id: str, name: str,
                              params: Optional[dict] = None) -> dict:
        """Create a cluster-persistent task (PersistentTasksService#
        sendStartRequest): the leader folds it into state, assigns it to a
        live node, and reassigns on node loss."""
        self._submit_to_leader({"kind": "persistent_task_start",
                                "id": task_id, "name": name,
                                "params": params or {}})
        return {"acknowledged": True, "task_id": task_id}

    def remove_persistent_task(self, task_id: str) -> dict:
        """Cancel + remove (sendRemoveRequest): the owning node's reconcile
        observes the removal and cancels the local executor."""
        self._submit_to_leader({"kind": "persistent_task_remove",
                                "id": task_id})
        return {"acknowledged": True}

    def list_persistent_tasks(self) -> dict:
        return dict((self._data().get("persistent_tasks") or {}))

    def _apply_remote_settings(self, settings: dict):
        """cluster.remote.<alias>.seeds handling for _cluster/settings:
        the registry is published THROUGH cluster state so every
        coordinator (and any node applying the state later) registers the
        remote, not just the node that served the PUT."""
        remotes = {}
        for k, v in list(settings.items()):
            parts = k.split(".")
            if len(parts) == 4 and parts[0] == "cluster" \
                    and parts[1] == "remote" and parts[3] == "seeds":
                alias = parts[2]
                if not v:
                    remotes[alias] = None
                else:
                    remotes[alias] = v[0] if isinstance(v, list) else v
        if remotes:
            self._submit_to_leader({"kind": "remote_clusters",
                                    "remotes": remotes})
        return bool(remotes)

    def _on_ccs_query(self, sender: str, payload: dict):
        """Remote-cluster side of CCS: run this cluster's own scatter and
        return candidates + agg partials + the shard→node map the fetch
        call must echo back (the remote reduce half of ccsRemoteReduce)."""
        cands, partials, total, shard_nodes, n_shards, skipped = \
            self._cluster_query_phase(payload["index"], payload["body"],
                                      payload["k"])
        return {"candidates": Opaque(
                    [(c.score, c.seg_i, c.ord, c.sort_values, c.shard_i)
                     for c in cands]),
                "partials": Opaque(partials),
                "total": total,
                "shard_nodes": {str(k): v for k, v in shard_nodes.items()},
                "n_shards": n_shards, "skipped": skipped}

    def _on_ccs_fetch(self, sender: str, payload: dict):
        from opensearch_tpu.search.executor import _Candidate
        cands = [_Candidate(s, g, o, sv, shard_i=si)
                 for s, g, o, sv, si in _unwrap(payload["docs"])]
        shard_nodes = {int(k): v
                       for k, v in payload["shard_nodes"].items()}
        hit_map = self._cluster_fetch(payload["index"], payload["body"],
                                      cands, shard_nodes)
        return {"hits": Opaque(
            [hit_map[(c.shard_i, c.seg_i, c.ord)] for c in cands])}

    def search_ccs(self, expression: str, body: Optional[dict]) -> dict:
        """Cross-cluster + multi-index search: `remote:idx,local_idx`.

        Per-cluster query phases run concurrently (each remote coordinator
        reduces its own shards first — the ccsMinimizeRoundtrips shape of
        TransportSearchAction.java:422), then the local coordinator merges
        candidates and aggregation partials (SearchResponseMerger.java:88)
        and fetches page hits from their owning clusters."""
        from opensearch_tpu.search.aggs.parse import parse_aggs
        from opensearch_tpu.search.aggs.pipeline import apply_pipelines
        from opensearch_tpu.search.aggs.reduce import reduce_aggs
        from opensearch_tpu.search.controller import (
            _compare_candidates, _parse_sort)
        from opensearch_tpu.search.executor import _Candidate

        body = body or {}
        start = time.monotonic()
        sort_specs = _parse_sort(body.get("sort"))
        if list(sort_specs) != [("_score", "desc")]:
            raise IllegalArgumentError(
                "cross-cluster search supports _score sorting only")
        size = int(body.get("size", 10))
        from_ = int(body.get("from", 0))
        k = max(from_ + size, 10)

        targets: List[Tuple[Optional[str], str]] = []   # (alias|None, idx)
        for part in expression.split(","):
            part = part.strip()
            if not part:
                continue
            if ":" in part:
                alias, idx = part.split(":", 1)
                if alias not in self._remotes:
                    raise IllegalArgumentError(
                        f"no such remote cluster [{alias}]")
                targets.append((alias, idx))
            else:
                targets.append((None, part))

        # per-cluster query phases (parallel); candidates are tagged with
        # their target index so the fetch + rendering know the origin
        results: Dict[int, dict] = {}
        errors: List[Exception] = []
        lock = threading.Lock()

        def query_target(ti: int, alias: Optional[str], idx: str):
            try:
                if alias is None:
                    cands, partials, total, shard_nodes, n_shards, \
                        skipped = self._cluster_query_phase(idx, body, k)
                    out = {"cands": cands, "partials": partials,
                           "total": total, "shard_nodes": shard_nodes,
                           "n_shards": n_shards, "skipped": skipped}
                else:
                    resp = self.transport.send_sync(
                        self._remotes[alias], CCS_QUERY,
                        {"index": idx, "body": body, "k": k},
                        timeout=60.0)
                    cands = [_Candidate(s, g, o, sv, shard_i=si)
                             for s, g, o, sv, si in
                             _unwrap(resp["candidates"])]
                    out = {"cands": cands,
                           "partials": _unwrap(resp["partials"]),
                           "total": resp["total"],
                           "skipped": resp.get("skipped", 0),
                           "shard_nodes": resp["shard_nodes"],
                           "n_shards": resp["n_shards"]}
                with lock:
                    results[ti] = out
            except Exception as e:
                errors.append(e)

        threads = [threading.Thread(target=query_target, args=(ti, a, i),
                                    daemon=True)
                   for ti, (a, i) in enumerate(targets)]
        for th in threads:
            th.start()
        for th in threads:
            th.join(65.0)
        if errors:
            raise errors[0]
        if len(results) < len(targets):
            missing = [f"{a or '_local'}:{i}" for ti, (a, i)
                       in enumerate(targets) if ti not in results]
            raise OpenSearchTpuError(
                f"cross-cluster query phase timed out for {missing}")

        # merge: score desc, tie-break by target order then shard/seg/doc
        merged: List[Tuple] = []
        total = 0
        n_shards = 0
        skipped = 0
        all_partials: List = []
        for ti in range(len(targets)):
            out = results[ti]
            total += out["total"]
            n_shards += out["n_shards"]
            skipped += out.get("skipped", 0)
            all_partials.extend(out["partials"])
            for c in out["cands"]:
                merged.append((ti, c))
        merged.sort(key=lambda tc: (-tc[1].score, tc[0], tc[1].shard_i,
                                    tc[1].seg_i, tc[1].ord))
        page = merged[from_:from_ + size]
        max_score = max((c.score for _, c in merged), default=None)

        # fetch per target cluster
        hits_by_pos: Dict[int, dict] = {}
        page_by_target: Dict[int, List[Tuple[int, Any]]] = {}
        for pos, (ti, c) in enumerate(page):
            page_by_target.setdefault(ti, []).append((pos, c))
        for ti, entries in page_by_target.items():
            alias, idx = targets[ti]
            cands = [c for _, c in entries]
            if alias is None:
                hit_map = self._cluster_fetch(
                    idx, body, cands, results[ti]["shard_nodes"])
                hits = [hit_map[(c.shard_i, c.seg_i, c.ord)]
                        for c in cands]
            else:
                resp = self.transport.send_sync(
                    self._remotes[alias], CCS_FETCH,
                    {"index": idx, "body": body,
                     "docs": Opaque([(c.score, c.seg_i, c.ord,
                                      c.sort_values, c.shard_i)
                                     for c in cands]),
                     "shard_nodes": results[ti]["shard_nodes"]},
                    timeout=60.0)
                hits = _unwrap(resp["hits"])
                for h in hits:
                    h["_index"] = f"{alias}:{h['_index']}"
            for (pos, _), hit in zip(entries, hits):
                hits_by_pos[pos] = hit

        resp = {
            "took": int((time.monotonic() - start) * 1000),
            "timed_out": False,
            "_shards": {"total": n_shards, "successful": n_shards,
                        "skipped": skipped, "failed": 0},
            "_clusters": {"total": len(targets),
                          "successful": len(targets), "skipped": 0},
            "hits": {"total": {"value": total, "relation": "eq"},
                     "max_score": max_score,
                     "hits": [hits_by_pos[p] for p in sorted(hits_by_pos)]},
        }
        agg_nodes = parse_aggs(body.get("aggs") or body.get("aggregations"))
        if agg_nodes:
            aggregations = reduce_aggs(all_partials)
            apply_pipelines(agg_nodes, aggregations)
            resp["aggregations"] = aggregations
        return resp

    # --------------------------------------------------------- REST surface

    def handle(self, method: str, path: str,
               params: Optional[Dict[str, str]] = None, body: Any = None,
               raw_body: Optional[bytes] = None,
               headers: Optional[Dict[str, str]] = None):
        """Cluster-routed dispatch for the data plane; everything else
        falls through to the local single-node surface."""
        from opensearch_tpu.rest.controller import RestResponse
        import json as _json

        if isinstance(body, (str, bytes)) and body:
            raw = body if isinstance(body, bytes) else body.encode()
            try:
                parsed = _json.loads(body)
            except (ValueError, UnicodeDecodeError):
                parsed = None
        else:
            raw = raw_body
            parsed = body

        try:
            routed = self._route(method.upper(), path.strip("/"), parsed,
                                 raw, params or {})
        except OpenSearchTpuError as e:
            routed = ({"error": {"type": e.error_type, "reason": str(e)},
                       "status": e.status}, e.status)
        if routed is not None:
            body_out, status = routed
            return RestResponse(status=status, body=body_out)
        return self.local.handle(method, path, params=params, body=parsed,
                                 raw_body=raw, headers=headers)

    def request(self, method: str, path: str, body: Any = None,
                **params) -> dict:
        resp = self.handle(method, path,
                           params={k: str(v) for k, v in params.items()},
                           body=body)
        out = resp.body if isinstance(resp.body, dict) \
            else {"_body": resp.body}
        out = dict(out)
        out["_status"] = resp.status
        return out

    def _route(self, method: str, path: str, body: Any, raw: Optional[bytes],
               params: Dict[str, str]) -> Optional[Tuple[dict, int]]:
        parts = [p for p in path.split("/") if p]
        if not parts:
            return None
        # cluster admin
        if parts[0] == "_cluster":
            if len(parts) >= 2 and parts[1] == "health":
                return self.cluster_health(), 200
            if len(parts) >= 2 and parts[1] == "state":
                return self.cluster_state_api(), 200
            if len(parts) >= 3 and parts[1] == "allocation" \
                    and parts[2] == "explain":
                return self.allocation_explain(body), 200
            if len(parts) >= 2 and parts[1] == "reroute" \
                    and method == "POST":
                commands = (body or {}).get("commands") or []
                dry_value = params.get("dry_run")
                # present-but-blank means true (RestRequest.bool_param
                # semantics: ?dry_run with no value is an enabled flag)
                dry = dry_value is not None and \
                    str(dry_value).lower() not in ("false", "0", "no")
                if dry:
                    # validate against a routing copy without publishing
                    from opensearch_tpu.cluster.allocation import (
                        apply_reroute_command)
                    trial = dict(self._data())
                    trial["routing"] = copy_routing(trial)
                    live = sorted(self.state.nodes) if self.state else []
                    for cmd in commands:
                        apply_reroute_command(trial, live, cmd)
                    return {"acknowledged": True, "dry_run": True}, 200
                self._submit_to_leader({"kind": "reroute",
                                        "commands": commands})
                # no routing snapshot in the response: a follower's applied
                # state may trail the leader's commit, and a stale table
                # here would read as "the move failed"
                return {"acknowledged": True}, 200
            if len(parts) >= 2 and parts[1] == "settings" \
                    and method == "PUT" and isinstance(body, dict):
                # intercept cluster.remote.*.seeds and allocation settings
                # (they must live in cluster state so every node's allocator
                # sees them), then fall through so the local settings
                # registry records the values too
                flat = {}
                for scope in ("persistent", "transient"):
                    flat.update(body.get(scope) or {})
                self._apply_remote_settings(flat)
                alloc = {k: v for k, v in flat.items()
                         if k.startswith("cluster.routing.")}
                if alloc:
                    self._submit_to_leader({"kind": "cluster_settings",
                                            "settings": alloc})
            return None
        if parts[0] == "_cat" and len(parts) > 1 and parts[1] == "shards":
            return self._cat_shards(), 200
        if parts[0] == "_cat" and len(parts) > 1 \
                and parts[1] == "allocation":
            data = self._data()
            counts: Dict[str, int] = {n: 0 for n in
                                      (self.state.nodes if self.state
                                       else [])}
            for shards in (data.get("routing") or {}).values():
                for e in shards:
                    for n in ([e.get("primary")] + e.get("replicas", [])):
                        if n in counts:
                            counts[n] += 1
            return {"_body": [{"shards": c, "node": n}
                              for n, c in sorted(counts.items())]}, 200
        if parts[0] == "_cat" and len(parts) > 1 \
                and parts[1] == "nodeattrs":
            attrs = self._data().get("node_attrs") or {}
            return {"_body": [{"node": n, "attr": a, "value": v}
                              for n in sorted(attrs)
                              for a, v in sorted(attrs[n].items())]}, 200
        if parts[0] == "_cat" and len(parts) > 1 \
                and parts[1] in ("cluster_manager", "master"):
            leader = self._leader_id()
            return {"_body": [{"id": leader, "node": leader}]}, 200
        if parts[0] == "_bulk" and method == "POST":
            return self._rest_bulk(None, raw), 200
        if parts[0].startswith("_"):
            return None
        name = parts[0]
        # index-level
        if len(parts) == 1:
            if method == "PUT":
                return self.create_index(name, body or {}), 200
            if method == "DELETE":
                return self.delete_index(name), 200
            return None
        sub = parts[1]
        if sub in ("_doc", "_bulk", "_search", "_count", "_msearch"):
            self._check_index_open(name)
        if sub == "_doc" and len(parts) >= 2:
            doc_id = parts[2] if len(parts) > 2 else None
            if method in ("PUT", "POST") and body is not None:
                if doc_id is None:
                    import secrets
                    doc_id = secrets.token_urlsafe(12)
                res = self.execute_bulk([{"op": "index", "index": name,
                                          "id": doc_id, "source": body,
                                          "routing": params.get("routing")}])
                item = res["items"][0]["index"]
                status = item.pop("status", 200)
                return {**item, "result": item.get("result", "created")}, \
                    status
            if method == "DELETE" and doc_id:
                res = self.execute_bulk([{"op": "delete", "index": name,
                                          "id": doc_id}])
                item = res["items"][0]["delete"]
                return item, item.pop("status", 200)
            if method == "GET" and doc_id:
                out = self.get_doc(name, doc_id,
                                   routing=params.get("routing"))
                return out, (200 if out["found"] else 404)
        if sub == "_bulk" and method == "POST":
            return self._rest_bulk(name, raw), 200
        if sub == "_search" and method in ("GET", "POST"):
            if params.get("search_type"):
                body = {**(body or {}),
                        "search_type": params["search_type"]}
            if "," in name or ":" in name:
                return self.search_ccs(name, body), 200
            return self.search(name, body), 200
        if sub == "_refresh" and method in ("POST", "GET"):
            return self.refresh_index(name), 200
        if sub == "_settings" and method == "PUT":
            return self.update_index_settings(name, body or {}), 200
        if sub == "_close" and method == "POST":
            return self.close_index(name), 200
        if sub == "_open" and method == "POST":
            return self.open_index(name), 200
        return None

    def _rest_bulk(self, default_index: Optional[str],
                   raw: Optional[bytes]) -> dict:
        import json as _json
        if not raw:
            raise IllegalArgumentError("bulk body required")
        lines = [ln for ln in raw.decode("utf-8").split("\n") if ln.strip()]
        ops = []
        i = 0
        while i < len(lines):
            action = _json.loads(lines[i])
            kind = next(iter(action))
            spec = action[kind] or {}
            index = spec.get("_index", default_index)
            doc_id = spec.get("_id")
            if kind == "delete":
                ops.append({"op": "delete", "index": index, "id": doc_id})
                i += 1
            else:
                source = _json.loads(lines[i + 1])
                if doc_id is None:
                    import secrets
                    doc_id = secrets.token_urlsafe(12)
                ops.append({"op": "index", "index": index, "id": doc_id,
                            "source": source,
                            "op_type": "create" if kind == "create"
                            else "index"})
                i += 2
        return self.execute_bulk(ops)

    # ----------------------------------------------------------- admin APIs

    def create_index(self, name: str, body: dict) -> dict:
        import uuid as _uuid
        from opensearch_tpu.indices.service import (
            _normalize_settings, validate_index_name)
        validate_index_name(name)
        settings = _normalize_settings(body.get("settings"))
        # the WHOLE normalized settings map goes into cluster state: the
        # allocator's deciders read index-level routing.allocation.* keys
        # from here (dropping them silently disabled index-level filters)
        meta = {"uuid": _uuid.uuid4().hex[:22],
                "settings": {**settings,
                             "number_of_shards":
                             int(settings.get("number_of_shards", 1)),
                             "number_of_replicas":
                             int(settings.get("number_of_replicas", 0))},
                "mappings": body.get("mappings") or {}}
        self._submit_to_leader({"kind": "create_index", "name": name,
                                "meta": meta})
        self._await(lambda: name in self._data().get("indices", {}))
        return {"acknowledged": True, "shards_acknowledged": True,
                "index": name}

    def delete_index(self, name: str) -> dict:
        self._index_meta(name)
        self._submit_to_leader({"kind": "delete_index", "name": name})
        self._await(lambda: name not in self._data().get("indices", {}))
        return {"acknowledged": True}

    def close_index(self, name: str) -> dict:
        """MetadataIndexStateService.closeIndices in cluster mode: the
        closed flag lives IN CLUSTER STATE, so every node's data plane
        rejects reads/writes for it (unlike a node-local flag)."""
        self._index_meta(name)
        self._submit_to_leader({"kind": "close_index", "name": name})
        self._await(lambda: self._data()["indices"]
                    .get(name, {}).get("closed"))
        return {"acknowledged": True, "shards_acknowledged": True,
                "indices": {name: {"closed": True}}}

    def open_index(self, name: str) -> dict:
        self._index_meta(name)
        self._submit_to_leader({"kind": "open_index", "name": name})
        self._await(lambda: not self._data()["indices"]
                    .get(name, {}).get("closed"))
        return {"acknowledged": True, "shards_acknowledged": True}

    def _check_index_open(self, name: str):
        if self._data().get("indices", {}).get(name, {}).get("closed"):
            from opensearch_tpu.common.errors import IndexClosedError
            raise IndexClosedError(name)

    def update_index_settings(self, name: str, body: dict) -> dict:
        """PUT /{index}/_settings in cluster mode: dynamic settings fold
        into the index metadata IN CLUSTER STATE (the allocator reads
        replicas counts and routing.allocation.* filters from there, and
        every fold ends with a reroute — MetadataUpdateSettingsService)."""
        from opensearch_tpu.indices.service import (_normalize_settings,
                                                    validate_dynamic_updates)
        self._index_meta(name)                  # 404 if absent
        updates = _normalize_settings(body or {})
        validate_dynamic_updates(updates)
        self._submit_to_leader({"kind": "update_index_settings",
                                "index": name, "settings": updates})

        def applied():
            meta = self._data().get("indices", {}).get(name) or {}
            settings = meta.get("settings") or {}
            return all(settings.get(k) == v if v is not None
                       else k not in settings
                       for k, v in updates.items())
        self._await(applied)
        return {"acknowledged": True}

    def _await(self, cond, timeout: float = 30.0):
        deadline = time.time() + timeout
        while time.time() < deadline:
            if cond():
                return
            time.sleep(0.02)
        raise OpenSearchTpuError("timed out waiting for cluster state")

    def await_health(self, status: str = "green", timeout: float = 60.0):
        rank = {"green": 2, "yellow": 1, "red": 0}
        self._await(lambda: rank[health_of(self._data())] >= rank[status],
                    timeout=timeout)

    def cluster_health(self) -> dict:
        data = self._data()
        st = self.state
        n_nodes = len(st.nodes) if st else 0
        active_p = active = unassigned = 0
        for shards in (data.get("routing") or {}).values():
            for entry in shards:
                if entry.get("primary"):
                    active_p += 1
                    active += 1
                else:
                    unassigned += 1
                active += len(entry.get("active_replicas", []))
                unassigned += (len(entry.get("replicas", []))
                               - len(entry.get("active_replicas", [])))
        return {"cluster_name": "opensearch-tpu",
                "status": health_of(data),
                "timed_out": False,
                "number_of_nodes": n_nodes,
                "number_of_data_nodes": n_nodes,
                "discovered_cluster_manager": self._leader_id() is not None,
                "active_primary_shards": active_p,
                "active_shards": active,
                "unassigned_shards": unassigned,
                "relocating_shards": 0, "initializing_shards": 0}

    def cluster_state_api(self) -> dict:
        st = self.state
        data = self._data()
        return {"cluster_manager_node": self._leader_id(),
                "version": st.version if st else 0,
                "nodes": {n: {"name": n, "attributes":
                              (data.get("node_attrs") or {}).get(n, {})}
                          for n in (st.nodes if st else [])},
                "metadata": {
                    "indices": data.get("indices", {}),
                    "persistent_tasks": {
                        "tasks": data.get("persistent_tasks", {})},
                    "cluster_settings": data.get("settings", {})},
                "routing_table": data.get("routing", {})}

    def _cat_shards(self) -> dict:
        rows = []
        for name, shards in (self._data().get("routing") or {}).items():
            for sid, entry in enumerate(shards):
                if entry.get("primary"):
                    rows.append({"index": name, "shard": sid, "prirep": "p",
                                 "state": "STARTED",
                                 "node": entry["primary"]})
                for r in entry.get("replicas", []):
                    rows.append({
                        "index": name, "shard": sid, "prirep": "r",
                        "state": "STARTED"
                        if r in entry.get("active_replicas", [])
                        else "INITIALIZING", "node": r})
        return {"_body": rows}


def copy_routing(data: dict) -> Dict[str, List[dict]]:
    """Deep-copy the routing table for mutation inside a state update."""
    return {name: [dict(e, replicas=list(e["replicas"]),
                        active_replicas=list(e["active_replicas"]))
                   for e in shards]
            for name, shards in (data.get("routing") or {}).items()}
