"""Framed TCP transport: the node-to-node RPC layer.

Re-design of the reference's custom TCP protocol (transport/TcpTransport.java
:117, TcpHeader.java:47, InboundPipeline.java:122, OutboundHandler.java,
TransportHandshaker.java:57):

frame = magic "OT" | u8 version | u8 flags | u64 request_id
      | u16 action_len | action | u32 payload_len | payload(JSON, serde.py)

flags: bit0 = response, bit1 = error, bit2 = zlib-compressed payload.

Each transport hosts ONE local node. Handler invocations and response
callbacks run on a single event-loop thread per transport — the analog of
the reference's transport-thread discipline (transport/Transports.java
asserts), which keeps the Coordinator single-threaded without locks.
Handlers registered with blocking=True (data-plane actions that fan out
sub-requests and wait) run on a worker pool instead, like the reference's
WRITE/SEARCH threadpools (threadpool/ThreadPool.java:92).
Version negotiation happens in a handshake request on connect
(action "internal:tcp/handshake").
"""

from __future__ import annotations

import queue
import socket
import ssl
import struct
import threading
import zlib
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Callable, Dict, Optional, Tuple

from opensearch_tpu.common.errors import NodeNotConnectedError
from opensearch_tpu.transport import serde
from opensearch_tpu.version import __version__

MAGIC = b"OT"
WIRE_VERSION = 1
FLAG_RESPONSE = 1
FLAG_ERROR = 2
FLAG_COMPRESSED = 4
COMPRESS_THRESHOLD = 1024
HEADER = struct.Struct(">2sBBQH")   # magic, version, flags, request_id, action_len
HANDSHAKE_ACTION = "internal:tcp/handshake"
# frame-size ceilings: segments cross the wire at recovery, so the general
# cap is generous; before a connection has handshaken only a tiny frame is
# admissible (a handshake fits in well under 64KB) — an unauthenticated
# peer cannot drive large allocations or a zlib inflation bomb
MAX_PAYLOAD = 1 << 30
MAX_PREAUTH_PAYLOAD = 1 << 16


def _write_frame(sock: socket.socket, flags: int, request_id: int,
                 action: str, payload: Any):
    body = serde.encode(payload)
    if len(body) >= COMPRESS_THRESHOLD:
        body = zlib.compress(body)
        flags |= FLAG_COMPRESSED
    action_b = action.encode("utf-8")
    frame = HEADER.pack(MAGIC, WIRE_VERSION, flags, request_id,
                        len(action_b)) + action_b + \
        struct.pack(">I", len(body)) + body
    sock.sendall(frame)


def _read_exact(sock: socket.socket, n: int) -> Optional[bytes]:
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            return None
        buf += chunk
    return buf


def _read_frame(sock: socket.socket, max_payload: int = MAX_PAYLOAD):
    head = _read_exact(sock, HEADER.size)
    if head is None:
        return None
    magic, version, flags, request_id, action_len = HEADER.unpack(head)
    if magic != MAGIC:
        raise ValueError("invalid frame magic (not an opensearch-tpu node?)")
    if version != WIRE_VERSION:
        raise ValueError(f"incompatible wire version [{version}]")
    action = _read_exact(sock, action_len).decode("utf-8")
    (payload_len,) = struct.unpack(">I", _read_exact(sock, 4))
    if payload_len > max_payload:
        raise ValueError(f"frame payload [{payload_len}] exceeds limit")
    body = _read_exact(sock, payload_len)
    if body is None:
        return None
    if flags & FLAG_COMPRESSED:
        # bounded inflate: a small compressed body must not be allowed to
        # decompress into unbounded memory (zip-bomb hardening)
        d = zlib.decompressobj()
        body = d.decompress(body, max_payload)
        if d.unconsumed_tail:
            raise ValueError("decompressed frame exceeds limit")
    return flags, request_id, action, serde.decode(body)


class ThreadedScheduler:
    """Real-clock scheduler satisfying the Coordinator's scheduler protocol
    (schedule_delayed/schedule_now/current_time_ms); tasks are posted to the
    transport's event loop so everything stays single-threaded."""

    def __init__(self, post: Callable[[Callable], None]):
        import random as _random
        import time as _time
        self._post = post
        self._time = _time
        self.random = _random.Random()
        self._timers = []
        self._closed = False

    @property
    def current_time_ms(self) -> int:
        return int(self._time.monotonic() * 1000)

    def schedule_now(self, fn, description=""):
        self._post(fn)

    def schedule_delayed(self, delay_ms: int, fn, description=""):
        if self._closed:
            return
        t = threading.Timer(delay_ms / 1000.0, lambda: self._post(fn))
        t.daemon = True
        t.start()
        self._timers.append(t)

    def close(self):
        self._closed = True
        for t in self._timers:
            t.cancel()


class TcpTransport:
    """One node's transport: server socket + outbound connections + event
    loop. Satisfies the same send/register_handler interface as the
    simulation transport, so the Coordinator runs on either."""

    def __init__(self, node_id: str, host: str = "127.0.0.1", port: int = 0,
                 threadpool=None, security=None):
        from opensearch_tpu.common.threadpool import ThreadPool
        self.node_id = node_id
        # TLS contexts + join-proof checker (transport/security.py);
        # None ⇒ plaintext, open admission (the default for tests)
        self.security = security
        self.handlers: Dict[str, Callable] = {}
        # the node's named-pool registry (ThreadPool.java:92); owned here
        # when the caller doesn't inject one (tests, bare transports)
        self.threadpool = threadpool or ThreadPool(node_name=node_id)
        self._owns_threadpool = threadpool is None
        # actions whose handlers may block (fan out sub-requests and wait)
        # run on their registered named pool, NOT the event loop — the
        # reference equivalently runs WRITE/SEARCH handlers on named
        # threadpools while coordination stays on the transport thread.
        # Cluster-admin actions (leader updates awaiting publication
        # commit, recovery segment shipping) register on the management
        # pool so they cannot starve the data plane.
        self._blocking_actions: set = set()
        self._action_pools: Dict[str, str] = {}
        # compat views used by non-handler background submitters
        self._workers = self.threadpool.executor("generic")
        self._mgmt_workers = self.threadpool.executor("management")
        # frames are written from the event loop AND worker threads (blocking
        # handlers answer on the inbound socket): serialize per socket or
        # concurrent sendall()s interleave and corrupt the frame stream
        self._write_locks: Dict[socket.socket, threading.Lock] = {}
        self._addresses: Dict[str, Tuple[str, int]] = {}
        self._connections: Dict[str, socket.socket] = {}
        self._pending: Dict[int, Tuple[Callable, Callable]] = {}
        self._request_counter = 0
        self._lock = threading.Lock()
        self._closed = False

        self._server = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._server.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._server.bind((host, port))
        self._server.listen(32)
        self.address = self._server.getsockname()

        self._loop_queue: "queue.Queue[Optional[Callable]]" = queue.Queue()
        self._loop_thread = threading.Thread(
            target=self._event_loop, name=f"transport-{node_id}", daemon=True)
        self._loop_thread.start()
        self._accept_thread = threading.Thread(
            target=self._accept_loop, name=f"accept-{node_id}", daemon=True)
        self._accept_thread.start()

        self.scheduler = ThreadedScheduler(self.post)
        self.register_handler(node_id, HANDSHAKE_ACTION, self._on_handshake)

    # -------------------------------------------------------------- registry

    def register_handler(self, node_id: str, action: str, handler: Callable,
                         blocking: bool = False, pool: str = "write"):
        assert node_id == self.node_id, "TcpTransport hosts one node"
        self.handlers[action] = handler
        if blocking:
            self._blocking_actions.add(action)
            self.threadpool.executor(pool)   # unknown pool name: raise now
            self._action_pools[action] = pool

    def register_node(self, node_id: str):  # interface parity with the mock
        pass

    def add_address(self, node_id: str, host: str, port: int):
        self._addresses[node_id] = (host, port)

    # ------------------------------------------------------------ event loop

    def post(self, fn: Callable):
        if not self._closed:
            self._loop_queue.put(fn)

    def _event_loop(self):
        while True:
            fn = self._loop_queue.get()
            if fn is None:
                return
            try:
                fn()
            except Exception:
                import traceback
                traceback.print_exc()

    # -------------------------------------------------------------- inbound

    def _accept_loop(self):
        while not self._closed:
            try:
                conn, _ = self._server.accept()
            except OSError:
                return
            threading.Thread(target=self._serve_inbound,
                             args=(conn,), daemon=True).start()

    def _serve_inbound(self, conn: socket.socket):
        """Per-connection thread: TLS-wrap first (the handshake blocks,
        so it must not run on the accept thread — a slow or hostile
        client would stall all accepts), then pump frames. A peer
        without a valid cert chain fails HERE, before any frame is
        read."""
        if self.security is not None and self.security.transport_tls:
            try:
                conn = self.security.wrap_transport_server(conn)
            except (ssl.SSLError, OSError):
                try:
                    conn.close()
                except OSError:
                    pass
                return
        self._read_loop(conn, outbound=False)

    def _read_loop(self, conn: socket.socket, outbound: bool = True):
        """Frame pump for one socket. Direction discipline (the trust
        gate the reference gets from InboundHandler's fixed readers +
        TransportHandshaker): accepted sockets carry REQUESTS only and
        must open with a handshake before any other action is processed
        (and until then only a tiny frame is admitted — see
        MAX_PREAUTH_PAYLOAD); sockets we initiated carry RESPONSES only
        (request ids correlate with our _pending map). A frame violating
        either rule closes the connection, so a peer that skips the
        handshake can neither invoke handlers nor spoof a response."""
        handshaken = False
        try:
            while not self._closed:
                frame = _read_frame(
                    conn, MAX_PAYLOAD if (outbound or handshaken)
                    else MAX_PREAUTH_PAYLOAD)
                if frame is None:
                    return
                flags, request_id, action, payload = frame
                if flags & FLAG_RESPONSE:
                    if not outbound:
                        return  # response on an inbound socket: spoofing
                    self.post(lambda f=flags, r=request_id, p=payload:
                              self._handle_response(f, r, p))
                    continue
                if outbound:
                    return  # peers never send requests on our sockets
                if not handshaken:
                    if action != HANDSHAKE_ACTION:
                        return  # un-handshaken peer: drop the connection
                    if self.security is not None:
                        body = payload.get("__body__") or {} \
                            if isinstance(payload, dict) else {}
                        sender = payload.get("__sender__", "") \
                            if isinstance(payload, dict) else ""
                        if not self.security.check_join_proof(
                                sender, body.get("proof")):
                            return  # wrong/absent shared-secret proof
                    handshaken = True
                if action in self._blocking_actions:
                    pool = self._action_pools.get(action, "write")
                    try:
                        self.threadpool.submit(
                            pool, self._handle_request, conn, request_id,
                            action, payload)
                    except Exception as e:
                        # pool-full rejection answers THIS request with an
                        # error frame (429) — it must not kill the shared
                        # connection and every other in-flight request
                        err = {"error": type(e).__name__, "reason": str(e),
                               "error_type": getattr(
                                   e, "error_type",
                                   "rejected_execution_exception"),
                               "status": getattr(e, "status", 429)}
                        self._locked_write(conn, FLAG_RESPONSE | FLAG_ERROR,
                                           request_id, action, err)
                else:
                    self.post(lambda c=conn, r=request_id, a=action,
                              p=payload: self._handle_request(c, r, a, p))
        except Exception:
            # any undecodable/hostile frame (bad magic, corrupt zlib,
            # rejected opaque payload) poisons the stream position — the
            # only safe recovery is dropping the connection, like the
            # reference on a corrupted inbound pipeline
            return
        finally:
            with self._lock:
                self._write_locks.pop(conn, None)
            for nid, s in list(self._connections.items()):
                if s is conn:
                    self._connections.pop(nid, None)
            try:
                conn.close()
            except OSError:
                pass

    def _locked_write(self, sock: socket.socket, flags: int,
                      request_id: int, action: str, payload: Any):
        with self._lock:
            wlock = self._write_locks.setdefault(sock, threading.Lock())
        with wlock:
            _write_frame(sock, flags, request_id, action, payload)

    def _handle_request(self, conn, request_id, action, payload):
        handler = self.handlers.get(action)
        try:
            if handler is None:
                raise NodeNotConnectedError(
                    f"no handler for [{action}] on [{self.node_id}]")
            sender = (payload or {}).get("__sender__", "?") \
                if isinstance(payload, dict) else "?"
            body = payload.get("__body__") if isinstance(payload, dict) \
                and "__body__" in payload else payload
            response = handler(sender, body)
            self._locked_write(conn, FLAG_RESPONSE, request_id, action,
                               response)
        except Exception as e:
            from opensearch_tpu.common.errors import OpenSearchTpuError
            err = {"error": type(e).__name__, "reason": str(e)}
            if isinstance(e, OpenSearchTpuError):
                err["error_type"] = e.error_type
                err["status"] = e.status
            try:
                self._locked_write(conn, FLAG_RESPONSE | FLAG_ERROR,
                                   request_id, action, err)
            except OSError:
                pass

    def _handle_response(self, flags, request_id, payload):
        with self._lock:
            callbacks = self._pending.pop(request_id, None)
        if callbacks is None:
            return
        on_response, on_failure = callbacks
        if flags & FLAG_ERROR:
            if on_failure is not None:
                if isinstance(payload, dict) and "error_type" in payload:
                    from opensearch_tpu.common.errors import \
                        RemoteTransportError
                    on_failure(RemoteTransportError(
                        payload.get("reason", ""),
                        error_type=payload["error_type"],
                        remote_status=int(payload.get("status", 500))))
                else:
                    on_failure(NodeNotConnectedError(
                        f"remote error: {payload.get('reason', payload)}"))
        elif on_response is not None:
            on_response(payload)

    # ------------------------------------------------------------- outbound

    def _connection_to(self, target: str) -> socket.socket:
        sock = self._connections.get(target)
        if sock is not None:
            return sock
        addr = self._addresses.get(target)
        if addr is None:
            raise NodeNotConnectedError(f"unknown node [{target}]")
        sock = socket.create_connection(addr, timeout=5)
        if self.security is not None and self.security.transport_tls:
            sock = self.security.wrap_transport_client(sock)
        sock.settimeout(None)
        self._connections[target] = sock
        threading.Thread(target=self._read_loop, args=(sock, True),
                         daemon=True).start()
        # open with a handshake frame so the peer's read loop admits the
        # connection before any substantive frame arrives (TCP ordering
        # guarantees it lands first); the response needs no waiter
        with self._lock:
            self._request_counter += 1
            hs_id = self._request_counter
        hs_body = {"version": __version__}
        if self.security is not None:
            proof = self.security.join_proof(self.node_id)
            if proof is not None:
                hs_body["proof"] = proof
        self._locked_write(sock, 0, hs_id, HANDSHAKE_ACTION,
                           {"__sender__": self.node_id,
                            "__body__": hs_body})
        return sock

    def send(self, sender: str, target: str, action: str, payload: Any,
             on_response: Optional[Callable] = None,
             on_failure: Optional[Callable] = None):
        with self._lock:
            self._request_counter += 1
            request_id = self._request_counter
            if on_response or on_failure:
                self._pending[request_id] = (on_response, on_failure)

        def do_send():
            try:
                sock = self._connection_to(target)
                wrapped = {"__sender__": sender, "__body__": payload}
                self._locked_write(sock, 0, request_id, action, wrapped)
            except Exception as e:
                self._connections.pop(target, None)
                with self._lock:
                    self._pending.pop(request_id, None)
                if on_failure is not None:
                    on_failure(e)

        self.post(do_send)
        return request_id

    def send_sync(self, target: str, action: str, payload: Any,
                  timeout: float = 30.0) -> Any:
        """Blocking request/response — for worker-pool/data-plane callers
        only (never call from the event loop: responses are dispatched
        there and would deadlock). Raises on remote error or timeout."""
        assert threading.current_thread() is not self._loop_thread, \
            "send_sync on the transport event loop would deadlock"
        done = threading.Event()
        box: list = [None, None]

        def ok(resp):
            box[0] = resp
            done.set()

        def fail(err):
            box[1] = err
            done.set()

        request_id = self.send(self.node_id, target, action, payload, ok,
                               fail)
        if not done.wait(timeout):
            # drop the abandoned callback so _pending can't grow unbounded
            # against a wedged peer (a very late response then no-ops)
            with self._lock:
                self._pending.pop(request_id, None)
            raise NodeNotConnectedError(
                f"timeout after {timeout}s awaiting [{action}] on [{target}]")
        if box[1] is not None:
            raise box[1] if isinstance(box[1], Exception) \
                else NodeNotConnectedError(str(box[1]))
        return box[0]

    # ------------------------------------------------------------ handshake

    def _on_handshake(self, sender: str, payload: dict):
        return {"node_id": self.node_id, "version": __version__,
                "wire_version": WIRE_VERSION}

    def handshake(self, target: str, on_response: Callable,
                  on_failure: Optional[Callable] = None):
        self.send(self.node_id, target, HANDSHAKE_ACTION,
                  {"version": __version__}, on_response,
                  on_failure or (lambda e: None))

    def probe_address(self, host: str, port: int,
                      timeout: float = 5.0) -> Optional[str]:
        """Dial a bare address and learn who answers — the
        HandshakingTransportAddressConnector step of seed-hosts discovery
        (a seed list names addresses, not node ids). Registers the real
        node id's address on success and returns it; None if nobody
        suitable answers."""
        probe_id = f"_probe_{host}:{port}"
        self.add_address(probe_id, host, port)
        try:
            resp = self.send_sync(probe_id, HANDSHAKE_ACTION,
                                  {"version": __version__}, timeout=timeout)
        except Exception:
            return None
        finally:
            self._addresses.pop(probe_id, None)
            # the probe connection is keyed under the placeholder id; drop
            # it so the real id dials a fresh, properly-keyed connection
            with self._lock:
                sock = self._connections.pop(probe_id, None)
            if sock is not None:
                try:
                    sock.close()
                except OSError:
                    pass
        node_id = (resp or {}).get("node_id")
        if not node_id or node_id == self.node_id:
            return None
        self.add_address(node_id, host, port)
        return node_id

    # --------------------------------------------------------------- close

    def close(self):
        self._closed = True
        self.scheduler.close()
        try:
            self._server.close()
        except OSError:
            pass
        for sock in list(self._connections.values()):
            try:
                sock.close()
            except OSError:
                pass
        self._loop_queue.put(None)
        if self._owns_threadpool:
            self.threadpool.shutdown()
