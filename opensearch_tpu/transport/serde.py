"""Wire serialization for transport payloads.

Re-design of the reference's StreamInput/StreamOutput + NamedWriteableRegistry
(common/io/stream/): polymorphic payloads are JSON with a `__type__` tag per
registered dataclass — the registry plays NamedWriteableRegistry's role of
mapping type names to readers. JSON keeps the wire debuggable; the frame
around it (tcp.py) is binary."""

from __future__ import annotations

import base64
import importlib
import io
import json
import pickle
from typing import Any, Callable, Dict

import numpy as np

from opensearch_tpu.cluster.coordination.core import (
    ClusterState, VotingConfiguration)

_WRITERS: Dict[type, Callable[[Any], dict]] = {}
_READERS: Dict[str, Callable[[dict], Any]] = {}


def register(type_name: str, cls: type, writer: Callable[[Any], dict],
             reader: Callable[[dict], Any]):
    _WRITERS[cls] = lambda v: {"__type__": type_name, **writer(v)}
    _READERS[type_name] = reader


register(
    "voting_config", VotingConfiguration,
    lambda v: {"node_ids": sorted(v.node_ids)},
    lambda d: VotingConfiguration(frozenset(d["node_ids"])))

register(
    "cluster_state", ClusterState,
    lambda s: {
        "term": s.term, "version": s.version, "nodes": sorted(s.nodes),
        "master_node": s.master_node,
        "last_committed_config": to_wire(s.last_committed_config),
        "last_accepted_config": to_wire(s.last_accepted_config),
        "data": s.data,
    },
    lambda d: ClusterState(
        term=d["term"], version=d["version"],
        nodes=frozenset(d["nodes"]), master_node=d["master_node"],
        last_committed_config=from_wire(d["last_committed_config"]),
        last_accepted_config=from_wire(d["last_accepted_config"]),
        data=d["data"]))


class Opaque:
    """Wrapper marking a payload subtree for binary transport — segment
    columns, candidate lists, decoded agg partials. Decoding uses a
    RESTRICTED unpickler: only the wire classes registered in
    `_OPAQUE_ALLOWED` (plus numpy's array-reconstruction machinery) may
    appear; any other global in the stream raises UnpicklingError before
    anything is instantiated. This mirrors the reference's trust model —
    InboundHandler only ever deserializes via fixed registered readers
    (transport/InboundHandler.java), never arbitrary classes."""

    __slots__ = ("value",)

    def __init__(self, value: Any):
        self.value = value


# (module, qualname) pairs the restricted unpickler may resolve. Kept as
# strings so registering a class does not import its module eagerly; the
# wire dataclasses (segments, compiled plans, agg partials) are all plain
# data — numpy arrays, strings, ints — with no side-effecting __reduce__.
_OPAQUE_ALLOWED = {
    ("opensearch_tpu.index.segment", "Segment"),
    ("opensearch_tpu.index.translog", "TranslogOp"),
    ("opensearch_tpu.index.segment", "TermMeta"),
    ("opensearch_tpu.index.segment", "FieldStats"),
    ("opensearch_tpu.index.segment", "DocValuesColumn"),
    ("opensearch_tpu.index.segment", "OrdinalsColumn"),
    ("opensearch_tpu.index.segment", "VectorColumn"),
    ("opensearch_tpu.ops.knn", "IVFIndex"),
    ("opensearch_tpu.search.compile", "Plan"),
    ("opensearch_tpu.search.aggs.engine", "AggPlan"),
    ("opensearch_tpu.search.aggs.reduce", "Decoded"),
    # numpy array/scalar/dtype reconstruction (module moved in numpy 2.x)
    ("numpy.core.multiarray", "_reconstruct"),
    ("numpy._core.multiarray", "_reconstruct"),
    ("numpy.core.multiarray", "scalar"),
    ("numpy._core.multiarray", "scalar"),
    ("numpy", "ndarray"),
    ("numpy", "dtype"),
    ("numpy", "bool_"),
    ("numpy.core.numeric", "_frombuffer"),
    ("numpy._core.numeric", "_frombuffer"),
    ("builtins", "complex"),
    ("builtins", "bytearray"),
    ("builtins", "frozenset"),
    ("builtins", "set"),
}


def allow_opaque(*classes: type):
    """Extension point: register additional wire-safe classes (plugins)."""
    for cls in classes:
        _OPAQUE_ALLOWED.add((cls.__module__, cls.__qualname__))


class _RestrictedUnpickler(pickle.Unpickler):
    def find_class(self, module: str, name: str):
        if (module, name) not in _OPAQUE_ALLOWED:
            raise pickle.UnpicklingError(
                f"opaque payload references disallowed global "
                f"[{module}.{name}]")
        obj: Any = importlib.import_module(module)
        for part in name.split("."):
            obj = getattr(obj, part)
        return obj


def _safe_loads(raw: bytes) -> Any:
    return _RestrictedUnpickler(io.BytesIO(raw)).load()


def safe_pickle_dumps(value: Any) -> bytes:
    """Raw restricted-codec bytes for out-of-band transfer (recovery file
    chunks): paired with safe_pickle_loads on the receiving side so the
    same allowlist gates reassembled blobs as gates inline Opaque frames."""
    return pickle.dumps(value, protocol=pickle.HIGHEST_PROTOCOL)


def safe_pickle_loads(raw: bytes) -> Any:
    return _safe_loads(raw)


# marker keys the codec itself emits — a *plain* dict from user data that
# happens to contain one of these must be escaped, or an attacker could
# smuggle a {"__pickle__": ...} doc body through the REST boundary and have
# a receiving node pickle.loads attacker bytes
_RESERVED_KEYS = frozenset(
    {"__type__", "__pickle__", "__ndarray__", "__escaped__"})


def to_wire(value: Any) -> Any:
    writer = _WRITERS.get(type(value))
    if writer is not None:
        return writer(value)
    if isinstance(value, Opaque):
        return {"__pickle__": base64.b64encode(
            pickle.dumps(value.value, protocol=pickle.HIGHEST_PROTOCOL)
        ).decode("ascii")}
    if isinstance(value, np.ndarray):
        return {"__ndarray__": base64.b64encode(
            np.ascontiguousarray(value).tobytes()).decode("ascii"),
            "dtype": str(value.dtype), "shape": list(value.shape)}
    if isinstance(value, (np.integer, np.floating, np.bool_)):
        return value.item()
    if isinstance(value, dict):
        out = {k: to_wire(v) for k, v in value.items()}
        if _RESERVED_KEYS & value.keys():
            return {"__escaped__": out}
        return out
    if isinstance(value, (list, tuple)):
        return [to_wire(v) for v in value]
    if isinstance(value, frozenset):
        return sorted(value)
    return value


def from_wire(value: Any) -> Any:
    if isinstance(value, dict):
        if "__escaped__" in value and len(value) == 1:
            # plain user dict that collided with marker keys: restore it
            # verbatim (recurse into values only — keys stay literal data)
            return {k: from_wire(v)
                    for k, v in value["__escaped__"].items()}
        type_name = value.get("__type__")
        if type_name is not None:
            reader = _READERS.get(type_name)
            if reader is None:
                raise ValueError(f"unknown wire type [{type_name}]")
            return reader({k: v for k, v in value.items()
                           if k != "__type__"})
        if "__pickle__" in value:
            return _safe_loads(base64.b64decode(value["__pickle__"]))
        if "__ndarray__" in value:
            return np.frombuffer(
                base64.b64decode(value["__ndarray__"]),
                dtype=np.dtype(value["dtype"])).reshape(value["shape"])
        return {k: from_wire(v) for k, v in value.items()}
    if isinstance(value, list):
        return [from_wire(v) for v in value]
    return value


def encode(payload: Any) -> bytes:
    return json.dumps(to_wire(payload), separators=(",", ":")).encode("utf-8")


def decode(raw: bytes) -> Any:
    return from_wire(json.loads(raw.decode("utf-8")))
