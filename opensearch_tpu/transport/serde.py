"""Wire serialization for transport payloads.

Re-design of the reference's StreamInput/StreamOutput + NamedWriteableRegistry
(common/io/stream/): polymorphic payloads are JSON with a `__type__` tag per
registered dataclass — the registry plays NamedWriteableRegistry's role of
mapping type names to readers. JSON keeps the wire debuggable; the frame
around it (tcp.py) is binary."""

from __future__ import annotations

import base64
import json
import pickle
from typing import Any, Callable, Dict

import numpy as np

from opensearch_tpu.cluster.coordination.core import (
    ClusterState, VotingConfiguration)

_WRITERS: Dict[type, Callable[[Any], dict]] = {}
_READERS: Dict[str, Callable[[dict], Any]] = {}


def register(type_name: str, cls: type, writer: Callable[[Any], dict],
             reader: Callable[[dict], Any]):
    _WRITERS[cls] = lambda v: {"__type__": type_name, **writer(v)}
    _READERS[type_name] = reader


register(
    "voting_config", VotingConfiguration,
    lambda v: {"node_ids": sorted(v.node_ids)},
    lambda d: VotingConfiguration(frozenset(d["node_ids"])))

register(
    "cluster_state", ClusterState,
    lambda s: {
        "term": s.term, "version": s.version, "nodes": sorted(s.nodes),
        "master_node": s.master_node,
        "last_committed_config": to_wire(s.last_committed_config),
        "last_accepted_config": to_wire(s.last_accepted_config),
        "data": s.data,
    },
    lambda d: ClusterState(
        term=d["term"], version=d["version"],
        nodes=frozenset(d["nodes"]), master_node=d["master_node"],
        last_committed_config=from_wire(d["last_committed_config"]),
        last_accepted_config=from_wire(d["last_accepted_config"]),
        data=d["data"]))


class Opaque:
    """Wrapper marking a payload subtree for binary (pickle) transport —
    segment columns, candidate lists, decoded agg partials. The analog of
    the reference sending Lucene file chunks / InternalAggregations as raw
    versioned bytes inside its frames: the cluster transport is a trusted,
    same-version boundary (handshake-verified), never exposed to clients,
    so pickle's arbitrary-code caveat is contained the same way the
    reference's arbitrary StreamInput readers are."""

    __slots__ = ("value",)

    def __init__(self, value: Any):
        self.value = value


# marker keys the codec itself emits — a *plain* dict from user data that
# happens to contain one of these must be escaped, or an attacker could
# smuggle a {"__pickle__": ...} doc body through the REST boundary and have
# a receiving node pickle.loads attacker bytes
_RESERVED_KEYS = frozenset(
    {"__type__", "__pickle__", "__ndarray__", "__escaped__"})


def to_wire(value: Any) -> Any:
    writer = _WRITERS.get(type(value))
    if writer is not None:
        return writer(value)
    if isinstance(value, Opaque):
        return {"__pickle__": base64.b64encode(
            pickle.dumps(value.value, protocol=pickle.HIGHEST_PROTOCOL)
        ).decode("ascii")}
    if isinstance(value, np.ndarray):
        return {"__ndarray__": base64.b64encode(
            np.ascontiguousarray(value).tobytes()).decode("ascii"),
            "dtype": str(value.dtype), "shape": list(value.shape)}
    if isinstance(value, (np.integer, np.floating, np.bool_)):
        return value.item()
    if isinstance(value, dict):
        out = {k: to_wire(v) for k, v in value.items()}
        if _RESERVED_KEYS & value.keys():
            return {"__escaped__": out}
        return out
    if isinstance(value, (list, tuple)):
        return [to_wire(v) for v in value]
    if isinstance(value, frozenset):
        return sorted(value)
    return value


def from_wire(value: Any) -> Any:
    if isinstance(value, dict):
        if "__escaped__" in value and len(value) == 1:
            # plain user dict that collided with marker keys: restore it
            # verbatim (recurse into values only — keys stay literal data)
            return {k: from_wire(v)
                    for k, v in value["__escaped__"].items()}
        type_name = value.get("__type__")
        if type_name is not None:
            reader = _READERS.get(type_name)
            if reader is None:
                raise ValueError(f"unknown wire type [{type_name}]")
            return reader({k: v for k, v in value.items()
                           if k != "__type__"})
        if "__pickle__" in value:
            return pickle.loads(base64.b64decode(value["__pickle__"]))
        if "__ndarray__" in value:
            return np.frombuffer(
                base64.b64decode(value["__ndarray__"]),
                dtype=np.dtype(value["dtype"])).reshape(value["shape"])
        return {k: from_wire(v) for k, v in value.items()}
    if isinstance(value, list):
        return [from_wire(v) for v in value]
    return value


def encode(payload: Any) -> bytes:
    return json.dumps(to_wire(payload), separators=(",", ":")).encode("utf-8")


def decode(raw: bytes) -> Any:
    return from_wire(json.loads(raw.decode("utf-8")))
