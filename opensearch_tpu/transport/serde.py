"""Wire serialization for transport payloads.

Re-design of the reference's StreamInput/StreamOutput + NamedWriteableRegistry
(common/io/stream/): polymorphic payloads are JSON with a `__type__` tag per
registered dataclass — the registry plays NamedWriteableRegistry's role of
mapping type names to readers. JSON keeps the wire debuggable; the frame
around it (tcp.py) is binary."""

from __future__ import annotations

import json
from typing import Any, Callable, Dict

from opensearch_tpu.cluster.coordination.core import (
    ClusterState, VotingConfiguration)

_WRITERS: Dict[type, Callable[[Any], dict]] = {}
_READERS: Dict[str, Callable[[dict], Any]] = {}


def register(type_name: str, cls: type, writer: Callable[[Any], dict],
             reader: Callable[[dict], Any]):
    _WRITERS[cls] = lambda v: {"__type__": type_name, **writer(v)}
    _READERS[type_name] = reader


register(
    "voting_config", VotingConfiguration,
    lambda v: {"node_ids": sorted(v.node_ids)},
    lambda d: VotingConfiguration(frozenset(d["node_ids"])))

register(
    "cluster_state", ClusterState,
    lambda s: {
        "term": s.term, "version": s.version, "nodes": sorted(s.nodes),
        "master_node": s.master_node,
        "last_committed_config": to_wire(s.last_committed_config),
        "last_accepted_config": to_wire(s.last_accepted_config),
        "data": s.data,
    },
    lambda d: ClusterState(
        term=d["term"], version=d["version"],
        nodes=frozenset(d["nodes"]), master_node=d["master_node"],
        last_committed_config=from_wire(d["last_committed_config"]),
        last_accepted_config=from_wire(d["last_accepted_config"]),
        data=d["data"]))


def to_wire(value: Any) -> Any:
    writer = _WRITERS.get(type(value))
    if writer is not None:
        return writer(value)
    if isinstance(value, dict):
        return {k: to_wire(v) for k, v in value.items()}
    if isinstance(value, (list, tuple)):
        return [to_wire(v) for v in value]
    if isinstance(value, frozenset):
        return sorted(value)
    return value


def from_wire(value: Any) -> Any:
    if isinstance(value, dict):
        type_name = value.get("__type__")
        if type_name is not None:
            reader = _READERS.get(type_name)
            if reader is None:
                raise ValueError(f"unknown wire type [{type_name}]")
            return reader({k: v for k, v in value.items()
                           if k != "__type__"})
        return {k: from_wire(v) for k, v in value.items()}
    if isinstance(value, list):
        return [from_wire(v) for v in value]
    return value


def encode(payload: Any) -> bytes:
    return json.dumps(to_wire(payload), separators=(",", ":")).encode("utf-8")


def decode(raw: bytes) -> Any:
    return from_wire(json.loads(raw.decode("utf-8")))
