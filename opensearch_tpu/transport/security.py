"""Transport/HTTP security: TLS contexts + cluster join authentication.

Re-designs the surface the reference gets from `libs/ssl-config`
(org.opensearch.common.ssl.SslConfiguration and its keystore/PEM loading)
plus the security plugin's node-to-node TLS: a small settings-driven
config object that yields ready `ssl.SSLContext`s for

- the node-to-node transport (MUTUAL TLS: both sides present certs and
  verify against the configured CA — an unauthenticated peer cannot even
  complete the TCP handshake, let alone join), and
- the HTTP layer (server cert; client verification optional).

Independent of (and composable with) TLS, `cluster.join.shared_secret`
gates the transport handshake with an HMAC proof: a peer that does not
know the secret is dropped at frame admission, before any handler runs.
The secret is a join/authorization gate, not a confidentiality mechanism
— on untrusted networks enable transport TLS as well (the reference's
security plugin likewise requires node-to-node TLS for its auth).

Settings (common/settings.py registry):
  transport.ssl.enabled                 bool   (default false)
  transport.ssl.certificate             path   (PEM cert for this node)
  transport.ssl.key                     path   (PEM private key)
  transport.ssl.certificate_authorities path   (PEM CA bundle)
  http.ssl.enabled                      bool
  http.ssl.certificate / http.ssl.key   paths
  http.ssl.certificate_authorities      path   (set → require client certs)
  cluster.join.shared_secret            string
"""

from __future__ import annotations

import hashlib
import hmac
import ssl
from typing import Any, Optional


class SecurityConfig:
    """Resolved TLS contexts + join secret for one node."""

    def __init__(self, settings: Optional[Any] = None):
        # accepts a plain dict or any object with .get (common/settings)
        get = (settings.get if settings is not None
               else lambda *_a, **_k: None)
        self.shared_secret: Optional[str] = \
            get("cluster.join.shared_secret") or None
        self._transport_server: Optional[ssl.SSLContext] = None
        self._transport_client: Optional[ssl.SSLContext] = None
        self._http_server: Optional[ssl.SSLContext] = None

        if _truthy(get("transport.ssl.enabled")):
            cert = get("transport.ssl.certificate")
            key = get("transport.ssl.key")
            ca = get("transport.ssl.certificate_authorities")
            if not (cert and key and ca):
                raise ValueError(
                    "transport.ssl.enabled requires certificate, key and "
                    "certificate_authorities")
            srv = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
            srv.load_cert_chain(cert, key)
            srv.load_verify_locations(ca)
            srv.verify_mode = ssl.CERT_REQUIRED      # mutual TLS
            self._transport_server = srv
            cli = ssl.SSLContext(ssl.PROTOCOL_TLS_CLIENT)
            cli.load_cert_chain(cert, key)
            cli.load_verify_locations(ca)
            cli.check_hostname = False   # cluster peers dial IPs; identity
            cli.verify_mode = ssl.CERT_REQUIRED  # comes from the CA chain
            self._transport_client = cli

        if _truthy(get("http.ssl.enabled")):
            cert = get("http.ssl.certificate")
            key = get("http.ssl.key")
            if not (cert and key):
                raise ValueError(
                    "http.ssl.enabled requires certificate and key")
            srv = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
            srv.load_cert_chain(cert, key)
            ca = get("http.ssl.certificate_authorities")
            if ca:
                srv.load_verify_locations(ca)
                srv.verify_mode = ssl.CERT_REQUIRED
            self._http_server = srv

    # ---------------------------------------------------------- transport

    @property
    def transport_tls(self) -> bool:
        return self._transport_server is not None

    def wrap_transport_server(self, sock):
        if self._transport_server is None:
            return sock
        return self._transport_server.wrap_socket(sock, server_side=True)

    def wrap_transport_client(self, sock):
        if self._transport_client is None:
            return sock
        return self._transport_client.wrap_socket(sock)

    # --------------------------------------------------------------- http

    @property
    def http_tls(self) -> bool:
        return self._http_server is not None

    def wrap_http_server_socket(self, sock):
        if self._http_server is None:
            return sock
        return self._http_server.wrap_socket(sock, server_side=True)

    # --------------------------------------------------------- join proof

    def join_proof(self, node_id: str) -> Optional[str]:
        """HMAC over the joining node's id: presented in the transport
        handshake, checked at frame admission (transport/tcp.py)."""
        if not self.shared_secret:
            return None
        return hmac.new(self.shared_secret.encode(),
                        f"join:{node_id}".encode(),
                        hashlib.sha256).hexdigest()

    def check_join_proof(self, node_id: str, proof: Optional[str]) -> bool:
        if not self.shared_secret:
            return True
        want = self.join_proof(node_id)
        return bool(proof) and hmac.compare_digest(want, str(proof))


def _truthy(v) -> bool:
    if isinstance(v, bool):
        return v
    return str(v).lower() in ("true", "1", "yes", "on")
