from opensearch_tpu.transport.tcp import TcpTransport, ThreadedScheduler

__all__ = ["TcpTransport", "ThreadedScheduler"]
