#!/usr/bin/env python
"""Kernel-level device-compute breakdown — render a kernel-profiler
snapshot as tables (ISSUE 19).

Input (auto-detected), any of:
  - a saved `GET /_telemetry/kernels` response ({"kernels": {...}});
  - a bare profiler snapshot ({"families": {...}, "census": {...}});
  - a `GET /_nodes/stats` dump (the nested telemetry.kernels block);
  - a BENCH_KERNELS_r*.json dump (per-(bench, family) rows from
    bench.py --kernels, one JSON record per line).

The report answers the question the five earlier observability layers
could not: WHICH executables own the device wall. Families rank by
estimated device-ms (timed rounds) falling back to compile-ms
(census-only snapshots); the roofline table marks each family compute-
vs memory-bound against the configured peak_flops/peak_bw ridge; the
census dump lists individual executables heaviest-compile first.

    python tools/kernel_report.py KERNELS.json
    curl -s localhost:9200/_telemetry/kernels | \\
        python tools/kernel_report.py -
    python tools/kernel_report.py --top 5 BENCH_KERNELS_r01.json
    python tools/kernel_report.py --assert-families 3 KERNELS.json
"""

from __future__ import annotations

import json
import os
import sys
from typing import List, Optional

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from trace_report import _render  # noqa: E402  (shared table renderer)


def load_snapshot(path: str) -> Optional[dict]:
    """Parse any supported dump into the profiler snapshot dict
    ({"families": ..., "census": ...}). '-' reads stdin. BENCH_KERNELS
    row dumps are up-converted into the same shape (one synthetic
    family per bench+family row, census-less)."""
    text = sys.stdin.read() if path == "-" else open(path).read()
    text = text.strip()
    if not text:
        return None
    candidates: List[dict] = []
    if text[0] == "[":
        candidates = [r for r in json.loads(text) if isinstance(r, dict)]
    else:
        for line in text.splitlines():
            line = line.strip()
            if not line:
                continue
            try:
                obj = json.loads(line)
            except json.JSONDecodeError:
                continue
            if isinstance(obj, dict):
                candidates.append(obj)
    bench_rows = []
    for rec in candidates:
        for block in (rec.get("kernels"),
                      (rec.get("telemetry") or {}).get("kernels")
                      if isinstance(rec.get("telemetry"), dict) else None,
                      rec):
            if isinstance(block, dict) and \
                    isinstance(block.get("families"), dict):
                return block
        if isinstance(rec.get("family"), str) and "device_ms" in rec:
            bench_rows.append(rec)
    if bench_rows:
        families = {}
        for r in bench_rows:
            name = f"{r.get('bench', '?')}/{r['family']}"
            families[name] = {
                "calls": r.get("calls", 0),
                "device_ms_est": r.get("device_ms", 0.0),
                "p50_ms": r.get("p50_ms"), "p99_ms": r.get("p99_ms"),
                "compiles": r.get("compiles", 0),
                "compile_ms": r.get("compile_ms", 0.0),
                "flops": r.get("flops"), "bytes": r.get("bytes"),
                "arithmetic_intensity": r.get("arithmetic_intensity"),
                "bound": r.get("bound", "unknown"),
            }
        return {"families": families, "census": {}}
    return None


def family_rows(snap: dict) -> List[dict]:
    """Flatten the per-family block into report rows, heaviest first by
    estimated device-ms (compile-ms breaks the tie for census-only
    families that never dispatched in the measured window)."""
    rows = []
    for fam, r in snap.get("families", {}).items():
        rows.append({
            "family": fam,
            "calls": r.get("calls", 0),
            "device_ms": r.get("device_ms_est", 0.0),
            "p50_ms": r.get("p50_ms"),
            "p99_ms": r.get("p99_ms"),
            "compiles": r.get("compiles", 0),
            "compile_ms": r.get("compile_ms", 0.0),
            "bound": r.get("bound", "unknown"),
        })
    rows.sort(key=lambda r: (-float(r["device_ms"] or 0.0),
                             -float(r["compile_ms"] or 0.0),
                             r["family"]))
    return rows


def render_families(rows: List[dict]) -> str:
    cols = ["family", "calls", "device_ms", "p50_ms", "p99_ms",
            "compiles", "compile_ms", "bound"]
    return _render([{c: r.get(c) for c in cols} for r in rows], cols)


def roofline_rows(snap: dict) -> List[dict]:
    """The roofline table: arithmetic intensity vs the configured ridge
    point, one row per family with known static cost."""
    rows = []
    for fam, r in snap.get("families", {}).items():
        ai = r.get("arithmetic_intensity")
        if ai is None:
            continue
        rows.append({
            "family": fam,
            "flops": r.get("flops"),
            "bytes": r.get("bytes"),
            "intensity": ai,
            "bound": r.get("bound", "unknown"),
        })
    rows.sort(key=lambda r: (-float(r["intensity"] or 0.0), r["family"]))
    return rows


def render_roofline(rows: List[dict], ridge: Optional[float]) -> str:
    cols = ["family", "flops", "bytes", "intensity", "bound"]
    table = _render([{c: r.get(c) for c in cols} for r in rows], cols)
    if ridge is not None:
        table += f"\nridge intensity (peak_flops/peak_bw): {ridge}"
    return table


def census_rows(snap: dict, top: int = 10) -> List[dict]:
    """Top individual executables from the census dump, heaviest
    compile first (the compile-cliff registry a warmup config reads)."""
    execs = (snap.get("census") or {}).get("executables") or []
    rows = [{
        "family": e.get("family"),
        "shape": e.get("shape"),
        "fingerprint": e.get("fingerprint"),
        "compile_ms": e.get("compile_ms"),
        "flops": e.get("flops"),
        "bytes": e.get("bytes"),
        "cost_source": e.get("cost_source"),
    } for e in execs]
    rows.sort(key=lambda r: -float(r["compile_ms"] or 0.0))
    return rows[:top]


def render_census(rows: List[dict]) -> str:
    cols = ["family", "shape", "fingerprint", "compile_ms", "flops",
            "bytes", "cost_source"]
    return _render([{c: r.get(c) for c in cols} for r in rows], cols)


def main(argv: List[str]) -> int:
    top = 10
    min_families = None
    args: List[str] = []
    rest = list(argv[1:])
    while rest:
        a = rest.pop(0)
        if a.startswith("--top"):
            top = int(a.split("=", 1)[1]) if "=" in a \
                else int(rest.pop(0))
        elif a.startswith("--assert-families"):
            min_families = int(a.split("=", 1)[1]) if "=" in a \
                else int(rest.pop(0))
        else:
            args.append(a)
    path = args[0] if args else "-"
    snap = load_snapshot(path)
    if snap is None:
        print("no kernel-profiler block found (the census is always-on "
              "after the first compile; for timed rows enable the "
              "profiler: POST /_telemetry/kernels/_enable, re-run "
              "traffic, or run bench.py --kernels)")
        return 1
    rows = family_rows(snap)
    census = snap.get("census") or {}
    print(f"{len(rows)} kernel famil{'y' if len(rows) == 1 else 'ies'}, "
          f"{census.get('entries', '?')} census executable(s), "
          f"compile total {census.get('compile_ms_total', '?')} ms "
          f"(sorted by device-ms, then compile-ms)")
    print(render_families(rows))
    rf = roofline_rows(snap)
    if rf:
        print("\nroofline (arithmetic intensity vs ridge):")
        print(render_roofline(rf, snap.get("ridge_intensity")))
    cr = census_rows(snap, top)
    if cr:
        print(f"\nexecutable census (top {len(cr)} by compile-ms):")
        print(render_census(cr))
    if min_families is not None and len(rows) < min_families:
        print(f"\nFAIL: {len(rows)} famil"
              f"{'y' if len(rows) == 1 else 'ies'} < {min_families}")
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv))
