"""Chaos sweep: enumerate every (fault site × fault kind) in
common/faults.py against a small corpus and verify the engine's
fault-tolerance contract (ISSUE 6):

  every injected-fault outcome is either
    - a differential-oracle-correct PARTIAL result with accurate
      `_shards.failures[]` (surviving shards' hits bit-identical to the
      unfaulted run), or
    - a clean TYPED error object —
  never an uncaught 500, never a corrupt page.

For each site the sweep picks the workload that actually reaches it
(single search, size=0 aggs, B=8 msearch envelope, hybrid, warmup
replay), installs one seeded rule, runs, classifies the outcome against
the site×kind expectation table, and re-checks the rendered page hit by
hit against the clean run (score equality — the corrupt-page check).
Two extra scenario rows cover the timeout contract (delayed shard +
timeout=10ms → `timed_out: true` partial) and per-item msearch
isolation (device fault downgrades one wave group's items only).

Exit 1 on any violated expectation; the site→outcome table prints
either way. `--fast` runs the exception+transient kinds only (the delay
rows add wall-clock, not coverage) — that subset is wired into tier-1
as tests/test_chaos_sweep.py (the sweep_delta pattern).
"""

from __future__ import annotations

import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

N_DOCS = 24

# site → the workload that reaches it (see WORKLOADS)
SITE_WORKLOAD = {
    "canmatch.shard": "search",
    "query.shard": "search",
    "query.dispatch": "search",
    "fetch.gather": "search",
    "request_cache.get": "aggs",
    "request_cache.put": "aggs",
    "reduce.aggs": "aggs",
    "warmup.replay": "warmup",
}

# (site, kind) → expected outcome class:
#   full        200, zero failed shards, page bit-identical to clean
#   partial     200, failed >= 1 with failures[], surviving-shard
#               differential holds (the oracle check)
#   typed_error 5xx allowed, but body.error.type must be present (a
#               clean typed error, never a raw stack-trace 500)
#   isolated    warmup replay: the faulted entry costs errors += 1,
#               never a raise out of warm_executor
# kind=delay expects "full" everywhere: a slow site is not a failed one.
EXPECT = {
    ("canmatch.shard", "exception"): "full",      # degrade: don't skip
    ("canmatch.shard", "transient"): "full",
    ("query.shard", "exception"): "partial",
    ("query.shard", "transient"): "partial",      # site not retry-wrapped
    ("query.dispatch", "exception"): "partial",
    ("query.dispatch", "transient"): "full",      # absorbed by retry
    ("fetch.gather", "exception"): "partial",
    ("fetch.gather", "transient"): "full",        # absorbed by retry
    ("request_cache.get", "exception"): "full",   # degrade to MISS
    ("request_cache.get", "transient"): "full",
    ("request_cache.put", "exception"): "full",   # dropped write
    ("request_cache.put", "transient"): "full",
    ("reduce.aggs", "exception"): "typed_error",  # no per-shard slice
    ("reduce.aggs", "transient"): "typed_error",
    ("warmup.replay", "exception"): "isolated",
    ("warmup.replay", "transient"): "full",       # absorbed by retry
}

SEARCH_BODY = {"query": {"match": {"msg": "module"}}, "size": N_DOCS}
AGGS_BODY = {"query": {"match": {"msg": "module"}}, "size": 0,
             "aggs": {"lv": {"terms": {"field": "level"}}}}


def build_corpus():
    """One node, two indices: logs (3 shards, text/keyword/integer) and
    hyb (2 shards, text + knn_vector) — small enough that the full sweep
    is tier-1-speed, sharded enough that partial results exist."""
    from opensearch_tpu.node import Node
    node = Node()
    node.request("PUT", "/logs", {
        "settings": {"number_of_shards": 3},
        "mappings": {"properties": {
            "msg": {"type": "text"}, "level": {"type": "keyword"},
            "code": {"type": "integer"}}}})
    lines = []
    for i in range(N_DOCS):
        lines.append(json.dumps({"index": {"_index": "logs",
                                           "_id": f"d{i}"}}))
        lines.append(json.dumps({
            "msg": f"error in module {i}" if i % 2 else f"ok module {i}",
            "level": "error" if i % 2 else "info", "code": i}))
    # single-shard twin of logs: the batched _msearch envelope (the
    # per-item isolation surface) only engages at num_shards == 1
    node.request("PUT", "/m1", {
        "settings": {"number_of_shards": 1},
        "mappings": {"properties": {
            "msg": {"type": "text"}, "level": {"type": "keyword"},
            "code": {"type": "integer"}}}})
    for i in range(N_DOCS):
        lines.append(json.dumps({"index": {"_index": "m1",
                                           "_id": f"d{i}"}}))
        lines.append(json.dumps({
            "msg": f"error in module {i}" if i % 2 else f"ok module {i}",
            "level": "error" if i % 2 else "info", "code": i}))
    node.request("PUT", "/hyb", {
        "settings": {"number_of_shards": 2},
        "mappings": {"properties": {
            "title": {"type": "text"},
            "vec": {"type": "knn_vector", "dimension": 4,
                    "method": {"space_type": "l2"}}}}})
    for i in range(12):
        lines.append(json.dumps({"index": {"_index": "hyb",
                                           "_id": f"h{i}"}}))
        lines.append(json.dumps({
            "title": "red dog" if i % 2 else "blue cat",
            "vec": [0.1 * i, 0.2, 0.3, 0.4]}))
    r = node.request("POST", "/_bulk", "\n".join(lines) + "\n",
                     refresh="true")
    assert r["_status"] == 200 and not r["errors"], r
    return node


def _shard_ids(node, index):
    out = []
    for shard in node.indices.get(index).shards:
        ids = []
        for seg in shard.executor.reader.segments:
            ids.extend(seg.doc_ids[o] for o in range(seg.num_docs)
                       if seg.live[o])
        out.append(ids)
    return out


def _hit_map(resp):
    return {h["_id"]: h["_score"] for h in resp["hits"]["hits"]}


def _clear_request_cache():
    from opensearch_tpu.indices.request_cache import REQUEST_CACHE
    REQUEST_CACHE.clear()


def _msearch(node, bodies, index="logs", **params):
    lines = []
    for b in bodies:
        lines.append(json.dumps({"index": index}))
        lines.append(json.dumps(b))
    resp = node.handle("POST", "/_msearch",
                       params={k: str(v) for k, v in params.items()},
                       body="\n".join(lines) + "\n")
    return resp.status, resp.body


def _check_page_integrity(resp, clean_hits, violations, row):
    """The corrupt-page check: every hit that DID render must carry the
    clean run's exact score for that id — a partial page may be smaller,
    never wrong."""
    for h in resp.get("hits", {}).get("hits", []):
        if h["_id"] not in clean_hits:
            violations.append(f"{row}: hit {h['_id']} not in clean run")
        elif clean_hits[h["_id"]] != h["_score"]:
            violations.append(
                f"{row}: hit {h['_id']} score {h['_score']} != clean "
                f"{clean_hits[h['_id']]} (corrupt page)")


def _classify(resp, expect, clean, surviving_oracle, row, violations):
    """Validate one response against its expectation class; returns the
    outcome cell for the table."""
    status = resp["_status"]
    failed = resp.get("_shards", {}).get("failed", 0)
    if status >= 500:
        etype = (resp.get("error") or {}).get("type")
        if not etype:
            violations.append(f"{row}: raw untyped {status}")
            return f"RAW-{status}"
        if expect != "typed_error":
            violations.append(
                f"{row}: expected {expect}, got {status} [{etype}] "
                f"(5xx-when-partial-expected)")
        return f"typed-{status} [{etype}]"
    if expect == "typed_error":
        violations.append(f"{row}: expected typed_error, got {status}")
        return f"{status} (expected error)"
    clean_hits = _hit_map(clean)
    _check_page_integrity(resp, clean_hits, violations, row)
    if expect == "full":
        if failed != 0:
            violations.append(f"{row}: expected full, failed={failed}")
        elif _hit_map(resp) != clean_hits:
            violations.append(f"{row}: full response != clean run")
        return f"full-200 failed=0"
    # expect == "partial"
    failures = resp.get("_shards", {}).get("failures", [])
    if failed < 1 or len(failures) != failed:
        violations.append(
            f"{row}: expected partial, failed={failed} "
            f"failures={len(failures)}")
        return f"200 failed={failed} (expected partial)"
    for f in failures:
        if not f.get("reason", {}).get("type"):
            violations.append(f"{row}: failures[] entry missing reason")
    # the differential oracle: hits == clean restricted to shards that
    # did NOT report a failure
    surviving = set()
    for si, ids in enumerate(surviving_oracle):
        if si not in {f["shard"] for f in failures}:
            surviving.update(ids)
    want = {d: s for d, s in clean_hits.items() if d in surviving}
    if _hit_map(resp) != want:
        violations.append(
            f"{row}: surviving-shard differential failed "
            f"({len(_hit_map(resp))} hits vs oracle {len(want)})")
    return f"partial-200 failed={failed}"


def _check_permits(node, row, violations):
    """The permit-leak invariant (ISSUE 11, extended to scheduler-queued
    requests in ISSUE 12): after a row quiesces, the backpressure gate
    must be back at baseline — current == 0 and the admitted/released
    counters equal — and the wave scheduler's queue must be EMPTY. A
    request stranded in the coalesce queue holds its permit forever
    (its thread blocks inside the acquire/release bracket), so a
    non-drained queue IS a permit leak in the making; checking both
    makes the invariant cover the window."""
    bp = node.search_backpressure
    if bp.current != 0 or bp.admitted_total != bp.released_total:
        violations.append(
            f"{row}: permit leak (current={bp.current}, "
            f"admitted={bp.admitted_total}, "
            f"released={bp.released_total})")
    sched = getattr(node, "wave_scheduler", None)
    if sched is not None and sched.queue_depth() != 0:
        violations.append(
            f"{row}: wave scheduler queue not drained "
            f"(depth={sched.queue_depth()})")


def _rule(site, kind):
    spec = {"site": site, "kind": kind, "seed": 0}
    if kind == "exception":
        spec["max_fires"] = 1       # one shard's slice, not the request
    elif kind == "delay":
        spec.update(delay_ms=5, max_fires=3)
    # transient at p=1 defaults to max_fires=1 (fail-once-then-succeed)
    return spec


def run_sweep(fast: bool = False):
    """Returns (table rows, violations). Each row is
    (site, kind, workload, outcome)."""
    import jax
    jax.config.update("jax_platforms", "cpu")
    from opensearch_tpu.common import faults

    faults.clear()
    node = build_corpus()
    kinds = ("exception", "transient") if fast \
        else ("exception", "transient", "delay")

    # clean baselines (also warm every executable so fault runs measure
    # fault handling, not compiles)
    clean_search = node.request("POST", "/logs/_search", SEARCH_BODY)
    clean_aggs = node.request("POST", "/logs/_search", AGGS_BODY)
    assert clean_search["_status"] == 200 and clean_aggs["_status"] == 200
    logs_shards = _shard_ids(node, "logs")
    hyb_shards = _shard_ids(node, "hyb")

    rows = []
    violations: list = []
    for site in sorted(faults.SITES):
        workload = SITE_WORKLOAD[site]
        for kind in kinds:
            row = f"{site}×{kind}"
            expect = "full" if kind == "delay" \
                else EXPECT[(site, kind)]
            faults.clear()
            _clear_request_cache()
            faults.install(_rule(site, kind))
            try:
                if workload == "warmup":
                    outcome = _run_warmup_combo(node, expect, row,
                                                violations)
                elif workload == "aggs":
                    resp = node.request("POST", "/logs/_search",
                                        AGGS_BODY)
                    outcome = _classify(resp, expect, clean_aggs,
                                        logs_shards, row, violations)
                    if (expect == "full" and resp["_status"] == 200 and
                            resp.get("aggregations")
                            != clean_aggs.get("aggregations")):
                        violations.append(f"{row}: agg tree != clean")
                else:
                    resp = node.request("POST", "/logs/_search",
                                        SEARCH_BODY)
                    outcome = _classify(resp, expect, clean_search,
                                        logs_shards, row, violations)
            finally:
                faults.clear()
            _check_permits(node, row, violations)
            rows.append((site, kind, workload, outcome))

    rows.extend(_scenario_rows(node, clean_search, logs_shards,
                               hyb_shards, violations, fast))
    _check_permits(node, "scenario-rows", violations)
    faults.clear()
    return rows, violations


def _run_warmup_combo(node, expect, row, violations):
    """warmup.replay: a faulted entry costs errors += 1 (exception) or a
    retried success (transient); warm_executor never raises."""
    from opensearch_tpu.search.warmup import WarmupRegistry
    executor = node.indices.get("logs").shards[0].executor
    reg = WarmupRegistry()
    reg.record("logs", {"query": {"match": {"msg": "module"}},
                        "size": 3}, 1, ("chaos-sig", "logs", 3))
    try:
        out = reg.warm_executor(executor)
    except Exception as e:
        violations.append(f"{row}: warm_executor raised "
                          f"{type(e).__name__}: {e}")
        return "RAISED"
    n = len(reg.entries())
    if expect == "isolated":
        if out["errors"] != n or out["warmed"] != 0:
            violations.append(f"{row}: expected all-entries-errored, "
                              f"got {out}")
        return f"isolated errors={out['errors']}"
    if out["warmed"] != n or out["errors"] != 0:
        violations.append(f"{row}: expected warmed={n}, got {out}")
    return f"warmed={out['warmed']}"


def _scenario_rows(node, clean_search, logs_shards, hyb_shards,
                   violations, fast):
    """The contract rows beyond the plain site×kind matrix: timeout,
    per-item msearch isolation, hybrid partial."""
    from opensearch_tpu.common import faults
    rows = []

    # ---- timeout: a delayed shard + timeout=10ms → timed_out partial
    faults.clear()
    _clear_request_cache()
    faults.install({"site": "query.shard", "kind": "delay",
                    "delay_ms": 60, "max_fires": 1})
    r = node.request("POST", "/logs/_search",
                     {**SEARCH_BODY, "timeout": "10ms"})
    faults.clear()
    if r["_status"] != 200 or r.get("timed_out") is not True:
        violations.append(
            f"timeout-scenario: status={r['_status']} "
            f"timed_out={r.get('timed_out')}")
    _check_page_integrity(r, _hit_map(clean_search), violations,
                          "timeout-scenario")
    rows.append(("query.shard", "delay+timeout=10ms", "search",
                 f"timed_out={r.get('timed_out')} "
                 f"hits={len(r['hits']['hits'])}"))

    # ---- msearch: a device fault downgrades ONE wave group's items to
    # per-item error objects; siblings match the clean run
    bodies = [{"query": {"match": {"msg": "module"}},
               "size": 5 if i % 2 else 20} for i in range(8)]
    faults.clear()
    _clear_request_cache()
    status, clean = _msearch(node, bodies, index="m1")
    assert status == 200
    _clear_request_cache()
    faults.install({"site": "query.dispatch", "kind": "exception",
                    "max_fires": 1})
    status, body = _msearch(node, bodies, index="m1")
    faults.clear()
    if status != 200:
        violations.append(f"msearch-scenario: envelope died ({status})")
    err_items = [it for it in body.get("responses", [])
                 if "error" in it]
    ok_items = [(i, it) for i, it in enumerate(body.get("responses", []))
                if "error" not in it]
    if not err_items or not ok_items:
        violations.append(
            f"msearch-scenario: expected one group failed + siblings "
            f"alive, got {len(err_items)} errors / {len(ok_items)} ok")
    for it in err_items:
        if not it.get("error", {}).get("type"):
            violations.append("msearch-scenario: untyped item error")
    for i, it in ok_items:
        if it["hits"] != clean["responses"][i]["hits"]:
            violations.append(
                f"msearch-scenario: surviving item {i} != clean")
    rows.append(("query.dispatch", "exception", "msearch B=8",
                 f"per-item errors={len(err_items)} "
                 f"ok={len(ok_items)}"))

    # ---- hybrid: one shard's fault costs one failures[] entry; the id
    # set equals clean ∩ surviving shards (scores shift with the
    # normalization bounds, membership must not)
    hyb_body = {"query": {"hybrid": {"queries": [
        {"match": {"title": "red dog"}},
        {"knn": {"vec": {"vector": [0.5, 0.2, 0.3, 0.4], "k": 4}}}]}},
        "size": 12, "_source": False}
    faults.clear()
    _clear_request_cache()
    clean_h = node.request("POST", "/hyb/_search", hyb_body)
    _clear_request_cache()
    faults.install({"site": "query.shard", "kind": "exception",
                    "max_fires": 1})
    r = node.request("POST", "/hyb/_search", hyb_body)
    faults.clear()
    if r["_status"] != 200 or r["_shards"]["failed"] != 1:
        violations.append(
            f"hybrid-scenario: status={r['_status']} "
            f"shards={r.get('_shards')}")
    else:
        failed_shard = r["_shards"]["failures"][0]["shard"]
        surviving = set()
        for si, ids in enumerate(hyb_shards):
            if si != failed_shard:
                surviving.update(ids)
        clean_ids = {h["_id"] for h in clean_h["hits"]["hits"]}
        got_ids = {h["_id"] for h in r["hits"]["hits"]}
        if got_ids != clean_ids & surviving:
            violations.append(
                "hybrid-scenario: surviving-shard membership "
                "differential failed")
    rows.append(("query.shard", "exception", "hybrid",
                 f"partial-200 failed="
                 f"{r.get('_shards', {}).get('failed')}"))
    return rows


def run_chaos_concurrent(clients: int = 4, n_requests: int = 96,
                         rate: float = 150.0, seed: int = 3,
                         node=None, scheduler: bool = False):
    """Chaos UNDER concurrency (ISSUE 11): seeded faults fire at
    `query.dispatch` (permanent, per-shard) and `fetch.gather`
    (transient, retry-absorbed) WHILE `clients` open-loop workers drive
    the REST search path on a Poisson schedule — the sequential sweep
    above proves per-row fault handling, this proves it while the
    permit gate, the wave engine and the retry helper are all
    contended.

    The contract checked (returns (summary, violations)):
      - zero 5xx: every completed request is a 200 (partial or full)
        or an admission 429 — a fault under concurrency must never
        escape as a raw error;
      - zero serve exceptions (the in-process path never raises);
      - zero permit leaks: the backpressure gate is back at baseline
        after the run (counter invariant, `_check_permits`);
      - goodput floor: >= 90% of requests complete as 200s (faults
        cost shard slices, not requests; admission sheds only under
        genuine pressure).

    Fault schedule: STAGGERED single-fire rules (skip + max_fires=1)
    instead of per-invocation probability draws. Same-site fire points
    sit further apart than any one request's invocation span, so no
    request can ever absorb more than one fire per site — at most 2 of
    its 3 shards fail, which the partial-failure contract renders as a
    200, NEVER the all-shards-failed 503. That makes "zero 5xx" a
    deterministic property of the schedule under ANY thread
    interleaving, not a probabilistic hope (a p=0.15 draw per
    invocation measurably lands 3 fires in one request and 503s)."""
    import json as _json

    sys.path.insert(0, os.path.join(REPO, "tools"))
    import openloop

    from opensearch_tpu.common import faults

    faults.clear()
    owns_node = node is None
    if owns_node:
        node = build_corpus()
    violations: list = []
    if scheduler:
        # ISSUE 12: the same chaos contract with the wave scheduler
        # COALESCING while the faults fire — per-wave fault isolation
        # must downgrade only the owning wave's items even when those
        # items belong to different coalesced requests, and the permit
        # invariant must hold across the window (checked below with
        # the queue-drained extension)
        node.wave_scheduler.set_enabled(True)
    # the scheduler variant drives the SINGLE-SHARD index so requests
    # actually coalesce (the scheduler only engages there); a
    # one-shard index has no partial-failure escape — one shard failed
    # IS all shards failed, a legitimate 503 — so its fault schedule
    # is transient-only: the bounded retry helper must absorb every
    # fire inside the shared waves
    path = "/m1/_search" if scheduler else "/logs/_search"
    # warm the executables so the measured window exercises fault
    # handling, not compiles
    clean = node.request("POST", path, SEARCH_BODY)
    assert clean["_status"] == 200, clean
    bodies = [{**SEARCH_BODY, "size": 4 + (i % 3) * 8}
              for i in range(n_requests)]
    for b in bodies[:6]:
        node.request("POST", path, b)
    base_admitted = node.search_backpressure.admitted_total
    base_released = node.search_backpressure.released_total

    statuses_5xx = []

    def serve(body):
        resp = node.handle("POST", path, body=_json.dumps(body))
        if resp.status >= 500:
            statuses_5xx.append((resp.status, resp.body))
        return resp.status

    # staggered deterministic fires (see docstring): a request spends 3
    # query.dispatch invocations (one per shard) and well under 100
    # fetch.gather invocations (page hits), so same-site gaps of 90 /
    # 400 guarantee one fire per site per request at most
    for skip in (10, 100, 190):
        faults.install({"site": "query.dispatch",
                        "kind": "transient" if scheduler
                        else "exception",
                        "skip": skip, "max_fires": 1})
    for skip in (50, 450, 850):
        faults.install({"site": "fetch.gather", "kind": "transient",
                        "skip": skip, "max_fires": 1})
    try:
        res = openloop.run_open_loop(serve, bodies, clients=clients,
                                     arrival_rate=rate, seed=seed)
    finally:
        faults.clear()
        if scheduler:
            # disable drains: every queued request completes before
            # the thread exits, so the depth check below sees 0 or a
            # real leak
            node.wave_scheduler.set_enabled(False)
    if scheduler and node.wave_scheduler.queue_depth() != 0:
        violations.append(
            f"concurrent-chaos: scheduler queue not drained "
            f"(depth={node.wave_scheduler.queue_depth()})")
    if statuses_5xx:
        violations.append(
            f"concurrent-chaos: {len(statuses_5xx)} 5xx response(s), "
            f"first: {str(statuses_5xx[0])[:200]}")
    if res["errors"]:
        violations.append(
            f"concurrent-chaos: {res['errors']} serve exception(s)")
    bp = node.search_backpressure
    if bp.current != 0 or \
            (bp.admitted_total - base_admitted) \
            != (bp.released_total - base_released):
        violations.append(
            f"concurrent-chaos: permit leak (current={bp.current}, "
            f"admitted+{bp.admitted_total - base_admitted}, "
            f"released+{bp.released_total - base_released})")
    if res["ok"] < 0.9 * n_requests:
        violations.append(
            f"concurrent-chaos: goodput floor broken "
            f"({res['ok']}/{n_requests} 200s)")
    summary = {"clients": clients, "n_requests": n_requests,
               "ok": res["ok"], "rejected": res["rejected"],
               "failed": res["failed"], "errors": res["errors"],
               "goodput_qps": res["goodput_qps"],
               "p99_ms": res["p99_ms"]}
    if scheduler:
        s = node.wave_scheduler.stats()
        summary["scheduler"] = {
            "dispatched_waves": s["dispatched_waves"],
            "coalesced": s["coalesced"],
            "co_batched_max": s["co_batched"]["max"],
            "shed_deadline": s["shed_deadline"]}
    return summary, violations


def main():
    fast = "--fast" in sys.argv
    if "--concurrency" in sys.argv:
        summary, violations = run_chaos_concurrent()
        print("chaos-under-concurrency:", json.dumps(summary))
        if violations:
            print(f"\n{len(violations)} contract violation(s):")
            for v in violations:
                print(" ", v)
            sys.exit(1)
        print("chaos-under-concurrency clean: zero 5xx, zero permit "
              "leaks, goodput floor held")
        return
    rows, violations = run_sweep(fast=fast)
    w_site = max(len(r[0]) for r in rows)
    w_kind = max(len(r[1]) for r in rows)
    w_load = max(len(r[2]) for r in rows)
    print(f"{'SITE':<{w_site}}  {'KIND':<{w_kind}}  "
          f"{'WORKLOAD':<{w_load}}  OUTCOME")
    for site, kind, workload, outcome in rows:
        print(f"{site:<{w_site}}  {kind:<{w_kind}}  "
              f"{workload:<{w_load}}  {outcome}")
    if violations:
        print(f"\n{len(violations)} contract violation(s):")
        for v in violations:
            print(" ", v)
        sys.exit(1)
    print(f"\nchaos sweep clean: {len(rows)} combos, every outcome a "
          "correct partial or a clean typed error")


if __name__ == "__main__":
    main()
