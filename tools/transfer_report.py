#!/usr/bin/env python
"""Per-channel transfer report from a ledger dump.

The transfer ledger (opensearch_tpu/telemetry/ledger.py) attributes every
host↔device transfer on the query path to a named channel; this tool
renders a dump of it as the table PROFILE.md rounds and ROADMAP item 1
work from: bytes / transfers / round-trips per channel and direction,
the device_get wall decomposition, and the implied tunnel bandwidth
(d2h bytes over device_get wall — the number on-device top-k/gather has
to beat by shrinking the numerator).

Input (auto-detected), any of:
  - a saved `GET /_telemetry/transfers` response
    ({"transfers": {...}, "device_memory": {...}});
  - a bare ledger snapshot ({"channels": ..., "device_get": ...});
  - a bench.py --telemetry output line (the snapshot rides at
    telemetry.transfers), or the BENCH_*.json file holding such lines
    (the first line carrying a ledger is reported).

    python tools/transfer_report.py transfers.json
    curl -s localhost:9200/_telemetry/transfers | python tools/transfer_report.py -
"""

from __future__ import annotations

import json
import sys
from typing import Any, List, Optional


def _find_snapshot(obj: Any) -> Optional[dict]:
    """Dig the ledger snapshot out of whichever wrapper it arrived in."""
    if not isinstance(obj, dict):
        return None
    if "channels" in obj and "device_get" in obj:
        return obj
    for key in ("transfers", "telemetry"):
        found = _find_snapshot(obj.get(key))
        if found is not None:
            return found
    return None


def load_snapshot(path: str) -> Optional[dict]:
    """Parse a dump file ('-' = stdin); JSONL files report the first
    line that carries a ledger snapshot."""
    text = sys.stdin.read() if path == "-" else open(path).read()
    text = text.strip()
    if not text:
        return None
    candidates: List[Any] = []
    if text[0] == "{" and "\n" in text:
        for line in text.splitlines():
            line = line.strip()
            if not line:
                continue
            try:
                candidates.append(json.loads(line))
            except json.JSONDecodeError:
                continue
    if not candidates:
        try:
            candidates = [json.loads(text)]
        except json.JSONDecodeError:
            return None
    for obj in candidates:
        snap = _find_snapshot(obj)
        if snap is not None:
            return snap
    return None


def _fmt_bytes(n: float) -> str:
    for unit in ("B", "KB", "MB", "GB"):
        if abs(n) < 1024.0 or unit == "GB":
            return f"{n:.1f}{unit}" if unit != "B" else f"{int(n)}B"
        n /= 1024.0
    return f"{n:.1f}GB"


def channel_rows(snap: dict) -> List[dict]:
    rows = []
    totals = snap.get("bytes_total", {})
    for direction in ("h2d", "d2h"):
        chans = snap.get("channels", {}).get(direction, {})
        dir_total = totals.get(direction, 0) or \
            sum(e.get("bytes", 0) for e in chans.values())
        for name in sorted(chans,
                           key=lambda c: -chans[c].get("bytes", 0)):
            ent = chans[name]
            rows.append({
                "channel": name,
                "dir": direction,
                "transfers": ent.get("transfers", 0),
                "round_trips": ent.get("round_trips", 0),
                "bytes": _fmt_bytes(ent.get("bytes", 0)),
                "pct_of_dir": round(
                    100.0 * ent.get("bytes", 0) / max(dir_total, 1), 1),
            })
    return rows


def render_table(rows: List[dict]) -> str:
    headers = ["channel", "dir", "transfers", "round_trips", "bytes",
               "pct_of_dir"]
    table = [headers] + [[str(r[h]) for h in headers] for r in rows]
    widths = [max(len(row[i]) for row in table)
              for i in range(len(headers))]
    return "\n".join(
        "  ".join(cell.ljust(w) for cell, w in zip(row, widths)).rstrip()
        for row in table)


def summary_lines(snap: dict) -> List[str]:
    get = snap.get("device_get", {})
    totals = snap.get("bytes_total", {})
    calls = get.get("calls", 0)
    total_ms = float(get.get("total_ms", 0.0))
    d2h = totals.get("d2h", 0)
    lines = [
        f"waves: {snap.get('waves', 0)}  device_get calls: {calls}  "
        f"device_get wall: {total_ms:.1f}ms",
        f"bytes h2d: {_fmt_bytes(totals.get('h2d', 0))}  "
        f"d2h: {_fmt_bytes(d2h)}",
    ]
    if total_ms > 0 and d2h:
        mbps = (d2h / 1e6) / (total_ms / 1e3)
        lines.append(f"implied d2h bandwidth: {mbps:.1f} MB/s "
                     f"({_fmt_bytes(d2h / max(calls, 1))}/round-trip)")
    pipeline = snap.get("pipeline") or {}
    if pipeline:
        lines.append(
            f"pipeline: inflight_waves={pipeline.get('inflight_waves', 0)}"
            f" max_inflight={pipeline.get('max_inflight_waves', 0)}"
            f" overlap={pipeline.get('overlap_ms', 0.0):.1f}ms over "
            f"{pipeline.get('overlap_events', 0)} wave(s)")
    rolling = snap.get("rolling") or {}
    for key, label in (("wave_bytes", "bytes/wave"),
                       ("wave_device_get_ms", "device_get ms/wave"),
                       ("wave_overlap_ms", "overlap ms/wave")):
        s = rolling.get(key)
        if s and s.get("count"):
            lines.append(
                f"rolling {label}: p50={s.get('p50')} p95={s.get('p95')} "
                f"p99={s.get('p99')} max={s.get('max')}")
    return lines


def main(argv: List[str]) -> int:
    path = argv[1] if len(argv) > 1 else "-"
    snap = load_snapshot(path)
    if snap is None:
        print("no transfer ledger found (enable it: "
              "POST /_telemetry/transfers/_enable — or bench.py "
              "--telemetry — then re-run traffic and dump "
              "GET /_telemetry/transfers)")
        return 1
    for line in summary_lines(snap):
        print(line)
    rows = channel_rows(snap)
    if rows:
        print(render_table(rows))
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv))
