#!/usr/bin/env python
"""Top shapes by device-ms — render a query-insights dump as a table.

Input (auto-detected), any of:
  - INSIGHTS_r*.json (bench.py --insights output: one JSON record per
    line, the insights block under "insights");
  - a saved `GET /_insights` response ({"insights": {...}});
  - a bare insights snapshot ({"shapes": {...}, "totals": {...}}).

The report answers the per-class questions ROADMAP items 3/4 need
(block-max pays per query class; the MaxSim tier's stage budget needs
per-class cost): which shape classes own the device wall, what they
scan, how well they coalesce, and who sends them.

    python tools/insights_report.py INSIGHTS_r01.json
    curl -s localhost:9200/_insights | python tools/insights_report.py -
    python tools/insights_report.py --metric scan INSIGHTS_r01.json
    python tools/insights_report.py --assert-shapes 3 INSIGHTS_r01.json
"""

from __future__ import annotations

import json
import os
import sys
from typing import Dict, List, Optional

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from trace_report import _render  # noqa: E402  (shared table renderer)

# --metric choices -> the shape-row key the table sorts by
SORT_KEYS = {"device": "device_ms_total", "latency": "took_total_ms",
             "scan": "_scan_bytes", "count": "count"}


def load_insights(path: str) -> Optional[dict]:
    """Parse any supported dump shape into the insights snapshot dict
    ({"shapes": ..., "totals": ...}). '-' reads stdin."""
    text = sys.stdin.read() if path == "-" else open(path).read()
    text = text.strip()
    if not text:
        return None
    candidates: List[dict] = []
    if text[0] == "[":
        candidates = [r for r in json.loads(text) if isinstance(r, dict)]
    else:
        for line in text.splitlines():
            line = line.strip()
            if not line:
                continue
            try:
                obj = json.loads(line)
            except json.JSONDecodeError:
                continue
            if isinstance(obj, dict):
                candidates.append(obj)
    for rec in candidates:
        for block in (rec.get("insights"), rec):
            if isinstance(block, dict) and \
                    isinstance(block.get("shapes"), dict):
                return block
    return None


def shape_rows(ins: dict, sort_key: str = "device_ms_total") \
        -> List[dict]:
    """Flatten the per-shape block into report rows, heaviest first by
    `sort_key`. Scan/transfer render in KB; co-batch as the ratio of
    requests that rode a shared wave."""
    rows = []
    for shape, r in ins.get("shapes", {}).items():
        scan = int(r.get("posting_bytes", 0)) + int(r.get("dense_bytes",
                                                          0))
        transfer = int(r.get("h2d_bytes", 0)) + int(r.get("d2h_bytes", 0))
        rows.append({
            "shape": shape,
            "kind": r.get("kind", "?"),
            "count": r.get("count", 0),
            "p50_ms": r.get("p50_ms"),
            "p99_ms": r.get("p99_ms"),
            "device_ms": round(float(r.get("device_ms_total", 0)), 1),
            "scan_kb": round(scan / 1024, 1),
            "transfer_kb": round(transfer / 1024, 1),
            "co_batch": r.get("co_batch_ratio", 0.0),
            "warm": r.get("warm_hits", 0),
            "compiled": r.get("compiled", 0),
            "cached": r.get("cached", 0),
            "kernel": r.get("dominant_kernel") or "-",
            "_scan_bytes": scan,
            "took_total_ms": round(float(r.get("took_total_ms", 0)), 1),
            "device_ms_total": float(r.get("device_ms_total", 0)),
        })
    rows.sort(key=lambda r: (-float(r.get(sort_key, 0) or 0),
                             r["shape"]))
    return rows


def render_shapes(rows: List[dict]) -> str:
    cols = ["shape", "kind", "count", "p50_ms", "p99_ms", "device_ms",
            "scan_kb", "transfer_kb", "co_batch", "warm", "compiled",
            "cached", "kernel"]
    return _render([{c: r.get(c) for c in cols} for r in rows], cols)


def render_top(ins: dict, size: int = 3) -> str:
    """The heavy-query registries: the top few capture records per
    metric, one compact line each."""
    out = []
    for metric, recs in (ins.get("top") or {}).items():
        out.append(f"top[{metric}]:")
        for rec in recs[:size]:
            out.append(
                f"  {rec.get('shape')}  took={rec.get('took_ms')}ms  "
                f"device={rec.get('device_ms')}ms  "
                f"scan={rec.get('scan_bytes')}B  "
                f"co_batched={rec.get('co_batched')}  "
                f"tenant={rec.get('tenant')}")
    return "\n".join(out)


def render_tenants(ins: dict) -> str:
    """Per-tenant request counts summed over shapes (who sends what)."""
    tenants: Dict[str, int] = {}
    for r in ins.get("shapes", {}).values():
        for t, n in (r.get("tenants") or {}).items():
            tenants[t] = tenants.get(t, 0) + int(n)
    rows = [{"tenant": t, "requests": n}
            for t, n in sorted(tenants.items(), key=lambda kv: -kv[1])]
    return _render(rows, ["tenant", "requests"]) if rows else ""


def main(argv: List[str]) -> int:
    metric = "device"
    min_shapes = None
    args: List[str] = []
    rest = list(argv[1:])
    while rest:
        a = rest.pop(0)
        if a.startswith("--metric"):
            metric = a.split("=", 1)[1] if "=" in a else rest.pop(0)
        elif a.startswith("--assert-shapes"):
            min_shapes = int(a.split("=", 1)[1]) if "=" in a \
                else int(rest.pop(0))
        else:
            args.append(a)
    if metric not in SORT_KEYS:
        print(f"unknown --metric {metric!r} "
              f"(one of {', '.join(sorted(SORT_KEYS))})")
        return 2
    path = args[0] if args else "-"
    ins = load_insights(path)
    if ins is None:
        print("no insights block found (enable the recorder: "
              "POST /_insights/_enable, then re-run traffic, or run "
              "bench.py --clients N --insights)")
        return 1
    rows = shape_rows(ins, SORT_KEYS[metric])
    totals = ins.get("totals", {})
    print(f"{len(rows)} shape class(es), "
          f"{totals.get('queries', '?')} request(s) attributed "
          f"(sorted by {metric})")
    print(render_shapes(rows))
    top = render_top(ins)
    if top:
        print("\nheavy-query registries (top captures per metric):")
        print(top)
    tns = render_tenants(ins)
    if tns:
        print("\nrequests by tenant:")
        print(tns)
    if min_shapes is not None and len(rows) < min_shapes:
        print(f"\nFAIL: {len(rows)} shape class(es) < {min_shapes}")
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv))
