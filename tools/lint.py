#!/usr/bin/env python3
"""CLI shim for the lint suite — the implementation lives in the
`tools/lint/` package (which shadows this module on the import path; this
file only exists so `python tools/lint.py` works from a checkout).

Exit code is the OR of failing rules' bits:
    1  sync-lint         2  retrace-lint      4  gate-lint
    8  shared-state-lint 16 except-breadth
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from lint.runner import main  # noqa: E402

if __name__ == "__main__":
    sys.exit(main())
