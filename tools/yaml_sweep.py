"""Run EVERY reference YAML REST suite against the in-process Node and
report which pass completely (candidates for tests/test_yaml_rest.py's
CURATED list). One fresh Node per test case, like the test runner."""
import json
import os
import sys
import traceback

import jax
jax.config.update("jax_platforms", "cpu")
REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
sys.path.insert(0, os.path.join(REPO, "tests"))

import yaml_rest_runner as yr  # noqa: E402
from opensearch_tpu.node import Node  # noqa: E402


def main():
    results = {}
    suites = []
    for root, _dirs, files in os.walk(yr.TEST_DIR):
        for f in files:
            if f.endswith(".yml"):
                suites.append(os.path.relpath(os.path.join(root, f),
                                              yr.TEST_DIR))
    suites.sort()
    for suite in suites:
        path = os.path.join(yr.TEST_DIR, suite)
        try:
            setup, teardown, tests = yr.load_suite(path)
        except Exception as e:
            results[suite] = {"load_error": str(e)[:120]}
            continue
        n_pass = n_skip = 0
        fails = []
        for name, steps in tests:
            node = Node()
            try:
                yr.run_case(node, setup, steps)
                n_pass += 1
            except yr.SkipTest:
                n_skip += 1
            except Exception as e:
                fails.append(f"{name}: {type(e).__name__}: {str(e)[:100]}")
        results[suite] = {"pass": n_pass, "skip": n_skip,
                          "fail": len(fails), "fails": fails[:2]}
        status = "FULL" if not fails and n_pass > 0 else \
            ("EMPTY" if n_pass == 0 and not fails else "PART")
        print(f"{status} {suite} pass={n_pass} skip={n_skip} "
              f"fail={len(fails)}", flush=True)
    full = [s for s, r in results.items()
            if r.get("fail") == 0 and r.get("pass", 0) > 0]
    print(f"\nFULL PASS: {len(full)}/{len(suites)}")
    with open(os.path.join(REPO, "YAML_SWEEP.json"), "w") as f:
        json.dump(results, f, indent=1)


if __name__ == "__main__":
    main()
