"""Shared infrastructure for the lint suite: file model, annotation
grammar, AST helpers.

Annotation grammar (one per comment, anywhere on the flagged line or any
line of a multi-line statement):

    # sync-ok: <channel>[ -- reason]       discharge a sync-lint finding;
                                           <channel> names the ledger
                                           channel the bytes belong to
                                           (`host` = provably host-only
                                           conversion, no device sync)
    # except-ok: <reason>                  discharge an exception-breadth
                                           finding (reason required)
    # retrace-ok: <reason>                 discharge a retrace-lint finding
    # shared-state-ok: <reason>            discharge a shared-state-lint
                                           finding (on the mutation line or
                                           on the module-level definition)
"""

from __future__ import annotations

import ast
import io
import os
import re
import tokenize
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

# rule id -> exit-code bit (tools/lint.py ORs the bits of failing rules)
RULE_BITS = {
    "sync-lint": 1,
    "retrace-lint": 2,
    "gate-lint": 4,
    "shared-state-lint": 8,
    "except-breadth": 16,
}

# ledger channel token: lowercase dotted names, e.g. `topk_ids`,
# `upload.literals`, `warmup.docvalues`, or the reserved `host`
CHANNEL_RE = re.compile(r"^[a-z][a-z0-9_]*(\.[a-z][a-z0-9_]*)*$")

ANNOTATION_RE = re.compile(
    r"#\s*(sync-ok|except-ok|retrace-ok|shared-state-ok)\s*:\s*(.*)")

# The serving query path: the files whose sync sites, exception breadth
# and shared mutable state the item-1/item-2 rewrites will churn. This
# list is the lint suite's source of truth (README "Static analysis").
QUERY_PATH_FILES = (
    "opensearch_tpu/search/executor.py",
    "opensearch_tpu/search/fetch.py",
    "opensearch_tpu/search/controller.py",
    "opensearch_tpu/search/canmatch.py",
    "opensearch_tpu/search/spmd.py",
    "opensearch_tpu/search/warmup.py",
    "opensearch_tpu/search/compile.py",
    "opensearch_tpu/search/plan_eval.py",
    "opensearch_tpu/search/aggs/engine.py",
    "opensearch_tpu/search/aggs/reduce.py",
    "opensearch_tpu/search/aggs/pipeline.py",
    "opensearch_tpu/indices/query_cache.py",
    "opensearch_tpu/indices/request_cache.py",
    "opensearch_tpu/parallel/distributed.py",
    "opensearch_tpu/searchpipeline/hybrid.py",
    "opensearch_tpu/searchpipeline/processors.py",
    "opensearch_tpu/ops/maxsim.py",
    "opensearch_tpu/telemetry/ledger.py",
    "opensearch_tpu/rest/actions.py",
)


@dataclass
class Violation:
    rule: str
    path: str           # repo-relative
    line: int
    message: str

    def to_dict(self) -> dict:
        return {"rule": self.rule, "path": self.path, "line": self.line,
                "message": self.message}

    def __str__(self) -> str:
        return f"{self.path}:{self.line}: [{self.rule}] {self.message}"


@dataclass
class Annotation:
    kind: str
    value: str          # channel for sync-ok, free-text reason otherwise
    line: int

    @property
    def channel(self) -> Optional[str]:
        """The channel token of a sync-ok annotation (first word; the
        rest is free-text reason), or None when malformed."""
        tok = self.value.split()[0] if self.value.split() else ""
        return tok if CHANNEL_RE.match(tok) else None


class SourceFile:
    """One parsed file: AST with parent links + per-line annotations."""

    def __init__(self, abspath: str, rel: str):
        self.abspath = abspath
        self.rel = rel
        with open(abspath, "r", encoding="utf-8") as f:
            self.text = f.read()
        self.tree = ast.parse(self.text, filename=rel)
        for node in ast.walk(self.tree):
            for child in ast.iter_child_nodes(node):
                child._lint_parent = node  # type: ignore[attr-defined]
        self.annotations: Dict[int, List[Annotation]] = {}
        self._scan_comments()

    def _scan_comments(self) -> None:
        try:
            toks = tokenize.generate_tokens(
                io.StringIO(self.text).readline)
            for tok in toks:
                if tok.type != tokenize.COMMENT:
                    continue
                m = ANNOTATION_RE.search(tok.string)
                if m:
                    line = tok.start[0]
                    self.annotations.setdefault(line, []).append(
                        Annotation(m.group(1), m.group(2).strip(), line))
        except tokenize.TokenError:
            pass

    # ------------------------------------------------------------- helpers

    def annotation_for(self, node: ast.AST, kind: str
                       ) -> Optional[Annotation]:
        """An annotation of `kind` on any line the node spans (so the
        comment can sit on whichever physical line of a wrapped call
        has room)."""
        start = getattr(node, "lineno", 0)
        end = getattr(node, "end_lineno", start) or start
        for line in range(start, end + 1):
            for a in self.annotations.get(line, ()):
                if a.kind == kind:
                    return a
        return None

    def enclosing_functions(self, node: ast.AST) -> List[ast.AST]:
        """Enclosing def/lambda chain, innermost first."""
        out = []
        cur = getattr(node, "_lint_parent", None)
        while cur is not None:
            if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef,
                                ast.Lambda)):
                out.append(cur)
            cur = getattr(cur, "_lint_parent", None)
        return out

    def ancestors(self, node: ast.AST) -> List[ast.AST]:
        out = []
        cur = getattr(node, "_lint_parent", None)
        while cur is not None:
            out.append(cur)
            cur = getattr(cur, "_lint_parent", None)
        return out


def repo_root(start: Optional[str] = None) -> str:
    """Walk up from `start` (default: this file) to the directory holding
    the opensearch_tpu package."""
    d = os.path.abspath(start or os.path.dirname(__file__))
    while True:
        if os.path.isdir(os.path.join(d, "opensearch_tpu")):
            return d
        parent = os.path.dirname(d)
        if parent == d:
            raise RuntimeError("repo root (opensearch_tpu/) not found")
        d = parent


def load_files(root: str, rels) -> List[SourceFile]:
    out = []
    for rel in rels:
        p = os.path.join(root, rel)
        if os.path.exists(p):
            out.append(SourceFile(p, rel))
    return out


def package_files(root: str) -> List[str]:
    """Every .py file under opensearch_tpu/, repo-relative."""
    out = []
    pkg = os.path.join(root, "opensearch_tpu")
    for dirpath, _dirnames, filenames in os.walk(pkg):
        for fn in sorted(filenames):
            if fn.endswith(".py"):
                out.append(os.path.relpath(os.path.join(dirpath, fn),
                                           root))
    return out


def func_params(fn) -> List[str]:
    """All parameter names of a def/lambda."""
    a = fn.args
    names = [p.arg for p in getattr(a, "posonlyargs", ())]
    names += [p.arg for p in a.args]
    if a.vararg:
        names.append(a.vararg.arg)
    names += [p.arg for p in a.kwonlyargs]
    if a.kwarg:
        names.append(a.kwarg.arg)
    return names


def name_of(node: ast.AST) -> str:
    """Dotted-ish source name of an expression, best effort."""
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        return f"{name_of(node.value)}.{node.attr}"
    if isinstance(node, ast.Call):
        return name_of(node.func)
    return ""


MUTABLE_CTORS = {"list", "dict", "set", "deque", "OrderedDict",
                 "defaultdict", "Counter"}


def module_mutable_globals(tree: ast.Module) -> Dict[str, int]:
    """Module-level names bound to mutable containers -> def line."""
    out: Dict[str, int] = {}
    for stmt in tree.body:
        targets: List[ast.expr] = []
        value: Optional[ast.expr] = None
        if isinstance(stmt, ast.Assign):
            targets, value = stmt.targets, stmt.value
        elif isinstance(stmt, ast.AnnAssign) and stmt.value is not None:
            targets, value = [stmt.target], stmt.value
        if value is None:
            continue
        mutable = isinstance(value, (ast.List, ast.Dict, ast.Set,
                                     ast.ListComp, ast.DictComp,
                                     ast.SetComp))
        if isinstance(value, ast.Call):
            callee = name_of(value.func).split(".")[-1]
            mutable = callee in MUTABLE_CTORS
        if not mutable:
            continue
        for t in targets:
            if isinstance(t, ast.Name):
                out[t.id] = stmt.lineno
    return out
