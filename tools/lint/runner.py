"""Lint runner: runs every checker, renders findings, exits with the OR
of the failing rules' bits (core.RULE_BITS) so CI can tell WHICH
discipline broke from the exit code alone. `--json` emits a
machine-readable report for tooling.
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import Dict, List, Optional

from . import gate_lint, retrace_lint, shared_state_lint, sync_lint
from .core import RULE_BITS, Violation, repo_root

# checker entry points; sync_lint owns two rule ids (sync-lint +
# except-breadth share one walker)
CHECKERS = (
    ("sync-lint / except-breadth", sync_lint.run),
    ("retrace-lint", retrace_lint.run),
    ("gate-lint", gate_lint.run),
    ("shared-state-lint", shared_state_lint.run),
)


def run_all(root: Optional[str] = None,
            rules: Optional[List[str]] = None) -> List[Violation]:
    root = root or repo_root()
    out: List[Violation] = []
    for _label, fn in CHECKERS:
        out.extend(fn(root))
    if rules:
        out = [v for v in out if v.rule in rules]
    out.sort(key=lambda v: (v.path, v.line, v.rule))
    return out


def exit_code(violations: List[Violation]) -> int:
    code = 0
    for v in violations:
        code |= RULE_BITS.get(v.rule, 32)
    return code


def main(argv: Optional[List[str]] = None) -> int:
    p = argparse.ArgumentParser(
        prog="tools/lint.py",
        description="Hot-path discipline linter (sync/retrace/gate/"
                    "shared-state + exception breadth)")
    p.add_argument("--root", default=None,
                   help="repo root (default: auto-detected)")
    p.add_argument("--json", action="store_true", dest="as_json",
                   help="machine-readable report on stdout")
    p.add_argument("--rule", action="append", default=None,
                   choices=sorted(RULE_BITS),
                   help="run/report only this rule id (repeatable)")
    args = p.parse_args(argv)

    root = args.root or repo_root()
    violations = run_all(root, args.rule)
    code = exit_code(violations)

    if args.as_json:
        by_rule: Dict[str, int] = {}
        for v in violations:
            by_rule[v.rule] = by_rule.get(v.rule, 0) + 1
        print(json.dumps({
            "root": root,
            "violations": [v.to_dict() for v in violations],
            "counts": by_rule,
            "exit_code": code,
            "rule_bits": RULE_BITS,
        }, indent=2))
        return code

    if not violations:
        print("lint: clean (sync-lint, except-breadth, retrace-lint, "
              "gate-lint, shared-state-lint)")
        return 0
    for v in violations:
        print(str(v))
    counts: Dict[str, int] = {}
    for v in violations:
        counts[v.rule] = counts.get(v.rule, 0) + 1
    summary = ", ".join(f"{r}: {n}" for r, n in sorted(counts.items()))
    print(f"\nlint: {len(violations)} violation(s) ({summary}); "
          f"exit code {code}")
    return code


if __name__ == "__main__":
    sys.exit(main())
