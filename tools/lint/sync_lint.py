"""sync-lint: every host<->device sync site on the query path must be
lexically inside a LedgerScope-carrying function or carry an explicit
`# sync-ok: <channel>` annotation naming its ledger channel.

Unattributed syncs are exactly what re-opened the bytes_to_device=0 gap
PR 7 closed: a `jax.device_get` (or an implicit sync — device-array
`.tolist()`, `np.asarray` on a device value, `.block_until_ready()`)
that no LedgerScope sees is a transfer the PROFILE.md decomposition
cannot explain, and a wall the ROADMAP item-1 rewrite cannot budget.

A function is "LedgerScope-carrying" when it demonstrably participates
in ledger attribution:
  - it takes a `scope` / `ledger_scope` / `ledger` parameter, or
  - its body calls the TransferLedger API (`note_device_get`, or
    `record`/`scope`/`ambient`/`attributed`/`tagged`/`current`/
    `new_wave` on a ledger-named object), or references `LedgerScope`,
    or
  - it BINDS a scope-named local — `state, scope = queue.get()`,
    `scope = wave.scope`, `for _, scope in pending:` — or passes a
    `scope=`/`ledger_scope=` keyword onward. This is the collector-
    thread pattern (the overlapped wave pipeline): a scope handed
    across a queue/thread boundary still counts as attribution, since
    the worker re-binds the request's LedgerScope before syncing.
Nested closures inherit: a `_collect` defined inside an attributing
function is attributed (the scope is in lexical reach).

The same walker owns the exception-breadth rule (`except-breadth`):
a blanket `except Exception` / bare `except` on the query path must be
narrowed to typed errors (common/errors.py, the PR 6 retry allowlist)
or carry `# except-ok: <reason>`.
"""

from __future__ import annotations

import ast
from typing import List

from .core import (QUERY_PATH_FILES, SourceFile, Violation, func_params,
                   load_files, name_of)

SYNC_RULE = "sync-lint"
EXCEPT_RULE = "except-breadth"

# parameter names that mark a function as receiving request attribution
SCOPE_PARAMS = {"scope", "ledger_scope", "ledger", "led_scope"}
# attribute calls that mark a function as performing attribution, when
# made on a ledger-named receiver
LEDGER_METHODS = {"record", "scope", "ambient", "attributed", "tagged",
                  "current", "new_wave"}
LEDGER_RECEIVERS = {"ledger", "_ledger", "led"}

BROAD_EXC = {"Exception", "BaseException"}


def _ledger_receiver(node: ast.expr) -> bool:
    """True when the receiver expression names the ledger (`_LEDGER`,
    `ledger`, `TELEMETRY.ledger`, `_tel.ledger`, ...)."""
    name = name_of(node).lower()
    if not name:
        return False
    last = name.split(".")[-1]
    return last in LEDGER_RECEIVERS or "ledger" in last


def _binds_scope_name(node: ast.AST) -> bool:
    """True when an assignment/loop target binds a scope-named local —
    the queue/thread-boundary handoff of the collector pattern."""
    targets: List[ast.expr] = []
    if isinstance(node, ast.Assign):
        targets = list(node.targets)
    elif isinstance(node, ast.AnnAssign):
        targets = [node.target]
    elif isinstance(node, ast.NamedExpr):
        targets = [node.target]
    elif isinstance(node, ast.For):
        targets = [node.target]
    elif isinstance(node, ast.withitem) and node.optional_vars is not None:
        targets = [node.optional_vars]
    for t in targets:
        for leaf in ast.walk(t):
            if isinstance(leaf, ast.Name) and leaf.id in SCOPE_PARAMS:
                return True
    return False


def is_ledger_carrying(fn) -> bool:
    """Does this def/lambda carry a LedgerScope (see module docstring)?"""
    if not isinstance(fn, ast.Lambda):
        if any(p in SCOPE_PARAMS for p in func_params(fn)):
            return True
    for node in ast.walk(fn):
        if isinstance(node, ast.Name) and node.id == "LedgerScope":
            return True
        if isinstance(node, ast.Attribute):
            if node.attr == "note_device_get":
                return True
            if node.attr in LEDGER_METHODS and _ledger_receiver(node.value):
                return True
        if _binds_scope_name(node):
            return True
        if isinstance(node, ast.Call):
            # forwarding a scope keyword marks participation the same
            # way receiving the parameter does
            if any(kw.arg in SCOPE_PARAMS for kw in node.keywords
                   if kw.arg is not None):
                return True
    return False


def _sync_kind(call: ast.Call) -> str:
    """'' when this call is not a sync site, else a label for the
    finding message."""
    fn = call.func
    if not isinstance(fn, ast.Attribute):
        return ""
    if fn.attr == "device_get" and isinstance(fn.value, ast.Name) \
            and fn.value.id == "jax":
        return "jax.device_get"
    if fn.attr == "block_until_ready":
        return ".block_until_ready()"
    if fn.attr == "tolist":
        return ".tolist()"
    if fn.attr == "asarray" and isinstance(fn.value, ast.Name) \
            and fn.value.id in ("np", "numpy", "_np"):
        return "np.asarray"
    return ""


def check_file(sf: SourceFile) -> List[Violation]:
    out: List[Violation] = []
    for node in ast.walk(sf.tree):
        # ---- sync sites -------------------------------------------------
        if isinstance(node, ast.Call):
            kind = _sync_kind(node)
            if kind:
                ann = sf.annotation_for(node, "sync-ok")
                if ann is not None:
                    if ann.channel is None:
                        out.append(Violation(
                            SYNC_RULE, sf.rel, node.lineno,
                            f"malformed sync-ok annotation "
                            f"[{ann.value!r}]: first token must be a "
                            f"ledger channel name"))
                    continue
                if any(is_ledger_carrying(f)
                       for f in sf.enclosing_functions(node)):
                    continue
                out.append(Violation(
                    SYNC_RULE, sf.rel, node.lineno,
                    f"{kind} outside any LedgerScope-carrying function; "
                    f"attribute it to the transfer ledger or annotate "
                    f"`# sync-ok: <channel>`"))
        # ---- exception breadth ------------------------------------------
        if isinstance(node, ast.ExceptHandler):
            broad = node.type is None or (
                isinstance(node.type, ast.Name)
                and node.type.id in BROAD_EXC) or (
                isinstance(node.type, ast.Tuple)
                and any(isinstance(e, ast.Name) and e.id in BROAD_EXC
                        for e in node.type.elts))
            if not broad:
                continue
            # a handler that only re-raises narrows nothing and hides
            # nothing — allowed without annotation
            if len(node.body) == 1 and isinstance(node.body[0], ast.Raise) \
                    and node.body[0].exc is None:
                continue
            if sf.annotation_for(node, "except-ok") is not None:
                continue
            label = "bare except" if node.type is None \
                else "except Exception"
            out.append(Violation(
                EXCEPT_RULE, sf.rel, node.lineno,
                f"{label} on the query path: narrow to typed errors "
                f"(common/errors.py / the retry allowlist) or annotate "
                f"`# except-ok: <reason>`"))
    return out


def run(root: str) -> List[Violation]:
    out: List[Violation] = []
    for sf in load_files(root, QUERY_PATH_FILES):
        out.extend(check_file(sf))
    return out
