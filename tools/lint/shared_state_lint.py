"""shared-state-lint: module-level mutable state mutated on the query
path must be lock-guarded or annotated — the thread-safety audit the
ROADMAP item-2 async wave scheduler needs before concurrent requests
share these modules.

The checker collects module-level names bound to mutable containers
(list/dict/set literals and constructors) in the query-path files, then
flags any mutation of those names inside a function body:

  - subscript/augmented assignment (`X[k] = v`, `X[0] += 1`),
  - mutating method calls (`X.append(...)`, `X.pop(...)`, ...),
  - rebinding via `global X`.

A mutation is discharged when it happens lexically under a `with` whose
context expression names a lock (`with _LOCK:`, `with self._lock:`), or
when annotated `# shared-state-ok: <reason>` — on the mutation line or
once on the module-level definition line (which blesses every mutation
of that name; use for GIL-atomic test counters). Registry-owned state
(metrics Counters, the warmup registry) is held behind objects with
their own locks and is not module-level mutable state, so it never
trips this rule — that is the pattern to migrate to.
"""

from __future__ import annotations

import ast
from typing import List

from .core import (QUERY_PATH_FILES, SourceFile, Violation, load_files,
                   module_mutable_globals, name_of)

RULE = "shared-state-lint"

MUTATORS = {"append", "extend", "insert", "add", "update", "setdefault",
            "pop", "popitem", "clear", "remove", "discard",
            "move_to_end", "appendleft", "popleft"}


def _lock_guarded(sf: SourceFile, node: ast.AST) -> bool:
    for anc in sf.ancestors(node):
        if isinstance(anc, ast.With):
            for item in anc.items:
                name = name_of(item.context_expr).lower()
                if "lock" in name:
                    return True
    return False


def _bound_locally(sf: SourceFile, node: ast.AST, name: str) -> bool:
    """Shadowed: the name is a parameter or assigned (non-global) inside
    an enclosing function."""
    for fn in sf.enclosing_functions(node):
        if isinstance(fn, ast.Lambda):
            continue
        from .core import func_params
        if name in func_params(fn):
            return True
        for n in ast.walk(fn):
            if isinstance(n, (ast.Assign, ast.AnnAssign)):
                targets = n.targets if isinstance(n, ast.Assign) \
                    else [n.target]
                for t in targets:
                    if isinstance(t, ast.Name) and t.id == name:
                        return True
    return False


def check_file(sf: SourceFile) -> List[Violation]:
    out: List[Violation] = []
    globals_ = module_mutable_globals(sf.tree)
    if not globals_:
        return out
    blessed = {name for name, line in globals_.items()
               if any(a.kind == "shared-state-ok"
                      for a in sf.annotations.get(line, ()))}

    def _flag(node, name, how):
        if name in blessed:
            return
        if sf.annotation_for(node, "shared-state-ok") is not None:
            return
        if _lock_guarded(sf, node):
            return
        if _bound_locally(sf, node, name):
            return
        out.append(Violation(
            RULE, sf.rel, node.lineno,
            f"unguarded mutation of module-level mutable [{name}] "
            f"({how}) on the query path: guard with a lock, move it "
            f"into a registry-owned structure (metrics counter), or "
            f"annotate `# shared-state-ok: <reason>`"))

    for node in ast.walk(sf.tree):
        if not sf.enclosing_functions(node):
            continue        # module-level init-time mutation is fine
        if isinstance(node, (ast.Assign, ast.AugAssign)):
            targets = node.targets if isinstance(node, ast.Assign) \
                else [node.target]
            for t in targets:
                if isinstance(t, ast.Subscript) and \
                        isinstance(t.value, ast.Name) and \
                        t.value.id in globals_:
                    _flag(node, t.value.id, "subscript assignment")
                elif isinstance(t, ast.Name) and t.id in globals_:
                    # plain rebinding only counts with a `global` decl
                    fn = sf.enclosing_functions(node)[0]
                    has_global = any(
                        isinstance(n, ast.Global) and t.id in n.names
                        for n in ast.walk(fn))
                    if has_global:
                        _flag(node, t.id, "global rebind")
        elif isinstance(node, ast.Call) and \
                isinstance(node.func, ast.Attribute) and \
                node.func.attr in MUTATORS and \
                isinstance(node.func.value, ast.Name) and \
                node.func.value.id in globals_:
            _flag(node, node.func.value.id,
                  f".{node.func.attr}() call")
    return out


def run(root: str) -> List[Violation]:
    out: List[Violation] = []
    for sf in load_files(root, QUERY_PATH_FILES):
        out.extend(check_file(sf))
    return out
