"""retrace-lint: functions handed to `jax.jit` must keep the
(plan-struct, shape-bucket) signature contract that makes AOT warmup
(search/warmup.py) work — a jitted function that silently retraces
turns the warmed executable cache into a lie.

Three lexical checks on every jit target the checker can resolve:

1. no closure over MUTABLE module globals: reading a module-level list/
   dict/set from inside a jitted body bakes the value at trace time
   while the name keeps mutating — the classic silent-staleness bug
   (closures over enclosing-function locals are fine: those are
   per-trace constants by construction);
2. no branching on tracer values: a Python `if`/`while` on a non-static
   parameter raises TracerBoolConversionError at best and forces a
   retrace per value at worst (params named in `static_argnums`/
   `static_argnames` are exempt);
3. no data-dependent shapes: `nonzero`/`unique`/`compress`/`.item()`
   and Python scalar casts (`int`/`float`/`bool`) of a parameter
   produce value-dependent shapes/values that cannot be traced.

Resolution is best effort and lexical: `jax.jit(name)` resolves through
enclosing scopes to a local def; `jax.jit(builder(...))` resolves one
level into module-level builders that `return <local def>` (the
executor's `build_*_query_phase` family); decorator forms `@jax.jit`
and `@functools.partial(jax.jit, ...)` are checked directly. Unresolvable
targets are skipped, not guessed at. Discharge with `# retrace-ok:
<reason>` on the flagged line.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from .core import (SourceFile, Violation, func_params, load_files,
                   module_mutable_globals, name_of, package_files)

RULE = "retrace-lint"

SHAPE_DEP_METHODS = {"nonzero", "unique", "compress", "item"}
SCALAR_CASTS = {"int", "float", "bool"}


def _is_jit_func(node: ast.expr) -> bool:
    return name_of(node) in ("jax.jit", "jit")


def _static_names(call: Optional[ast.Call], fn) -> Set[str]:
    """Parameter names excluded from tracing via static_argnums /
    static_argnames literals on the jit call (or partial)."""
    if call is None:
        return set()
    params = func_params(fn)
    out: Set[str] = set()
    for kw in call.keywords:
        vals: List[ast.expr] = []
        if isinstance(kw.value, (ast.Tuple, ast.List)):
            vals = list(kw.value.elts)
        else:
            vals = [kw.value]
        if kw.arg == "static_argnums":
            for v in vals:
                if isinstance(v, ast.Constant) and isinstance(v.value, int) \
                        and 0 <= v.value < len(params):
                    out.add(params[v.value])
        elif kw.arg == "static_argnames":
            for v in vals:
                if isinstance(v, ast.Constant) and isinstance(v.value, str):
                    out.add(v.value)
    return out


def _resolve_name(sf: SourceFile, at: ast.AST, name: str):
    """A FunctionDef named `name` visible from `at`: enclosing function
    bodies innermost-first, then module top level."""
    scopes = [f for f in sf.enclosing_functions(at)
              if not isinstance(f, ast.Lambda)]
    for scope in scopes + [sf.tree]:
        body = scope.body if not isinstance(scope, ast.Module) \
            else scope.body
        for stmt in body:
            if isinstance(stmt, ast.FunctionDef) and stmt.name == name:
                return stmt
    return None


def _resolve_builder(sf: SourceFile, call: ast.Call):
    """`jax.jit(builder(...))`: when `builder` is a module-level def whose
    return statement returns a locally defined closure, check THAT
    closure (the executor's build_*_query_phase family)."""
    if not isinstance(call.func, ast.Name):
        return None
    builder = None
    for stmt in sf.tree.body:
        if isinstance(stmt, ast.FunctionDef) and stmt.name == call.func.id:
            builder = stmt
            break
    if builder is None:
        return None
    local_defs = {s.name: s for s in builder.body
                  if isinstance(s, ast.FunctionDef)}
    for node in ast.walk(builder):
        if isinstance(node, ast.Return) and isinstance(node.value, ast.Name):
            if node.value.id in local_defs:
                return local_defs[node.value.id]
    return None


def _jit_targets(sf: SourceFile):
    """Yield (target_fn, jit_call_or_None, report_node) triples."""
    for node in ast.walk(sf.tree):
        # call form: jax.jit(target, ...)
        if isinstance(node, ast.Call) and _is_jit_func(node.func) \
                and node.args:
            arg = node.args[0]
            if isinstance(arg, (ast.Lambda,)):
                yield arg, node, node
            elif isinstance(arg, ast.Name):
                fn = _resolve_name(sf, node, arg.id)
                if fn is not None:
                    yield fn, node, node
            elif isinstance(arg, ast.Call):
                fn = _resolve_builder(sf, arg)
                if fn is not None:
                    yield fn, node, node
        # decorator forms: @jax.jit / @functools.partial(jax.jit, ...)
        if isinstance(node, ast.FunctionDef):
            for dec in node.decorator_list:
                if _is_jit_func(dec):
                    yield node, None, node
                elif isinstance(dec, ast.Call):
                    if _is_jit_func(dec.func):
                        yield node, dec, node
                    elif name_of(dec.func).endswith("partial") and \
                            dec.args and _is_jit_func(dec.args[0]):
                        yield node, dec, node


def _local_names(fn) -> Set[str]:
    """Names bound inside the function (params, assignments, loop vars,
    comprehension vars, nested defs) — these shadow module globals."""
    out = set(func_params(fn))

    def _bound_names(t):
        # only names the statement BINDS: `x = ...`, `x, y = ...` — NOT
        # the container of `x[0] = ...` / `x.attr = ...`, which reads x
        if isinstance(t, ast.Name):
            yield t.id
        elif isinstance(t, (ast.Tuple, ast.List)):
            for e in t.elts:
                yield from _bound_names(e)
        elif isinstance(t, ast.Starred):
            yield from _bound_names(t.value)

    for node in ast.walk(fn):
        if isinstance(node, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
            targets = node.targets if isinstance(node, ast.Assign) \
                else [node.target]
            for t in targets:
                out.update(_bound_names(t))
        elif isinstance(node, (ast.For, ast.comprehension)):
            t = node.target
            for n in ast.walk(t):
                if isinstance(n, ast.Name):
                    out.add(n.id)
        elif isinstance(node, ast.FunctionDef) and node is not fn:
            out.add(node.name)
    return out


def _check_target(sf: SourceFile, fn, jit_call, report) -> List[Violation]:
    out: List[Violation] = []
    mutable_globals = sf._lint_mutable_globals  # type: ignore[attr-defined]
    statics = _static_names(jit_call, fn)
    params = set(func_params(fn)) - statics
    locals_ = _local_names(fn)

    def _flag(node, msg):
        if sf.annotation_for(node, "retrace-ok") is None and \
                sf.annotation_for(report, "retrace-ok") is None:
            out.append(Violation(RULE, sf.rel, node.lineno, msg))

    body = fn.body if not isinstance(fn, ast.Lambda) else [fn.body]
    for stmt in body:
        for node in ast.walk(stmt):
            if isinstance(node, ast.Name) and \
                    isinstance(node.ctx, ast.Load) and \
                    node.id in mutable_globals and node.id not in locals_:
                _flag(node,
                      f"jitted function closes over mutable module "
                      f"global [{node.id}] (defined at line "
                      f"{mutable_globals[node.id]}): its value is baked "
                      f"at trace time while the name keeps mutating")
            tests: List[ast.expr] = []
            if isinstance(node, (ast.If, ast.While)):
                tests = [node.test]
            elif isinstance(node, ast.IfExp):
                tests = [node.test]
            for test in tests:
                hit = [n.id for n in ast.walk(test)
                       if isinstance(n, ast.Name) and n.id in params]
                if hit:
                    _flag(node,
                          f"jitted function branches on tracer "
                          f"value(s) {sorted(set(hit))}: data-dependent "
                          f"Python control flow forces a retrace per "
                          f"value (hoist to static_argnums or use "
                          f"lax.cond/jnp.where)")
            if isinstance(node, ast.Call):
                if isinstance(node.func, ast.Attribute) and \
                        node.func.attr in SHAPE_DEP_METHODS:
                    _flag(node,
                          f".{node.func.attr}() inside a jitted "
                          f"function produces a value/shape that "
                          f"depends on tracer data")
                elif isinstance(node.func, ast.Name) and \
                        node.func.id in SCALAR_CASTS and node.args and \
                        isinstance(node.args[0], ast.Name) and \
                        node.args[0].id in params:
                    _flag(node,
                          f"{node.func.id}() of tracer parameter "
                          f"[{node.args[0].id}] forces a concrete "
                          f"value inside a traced function")
    return out


def run(root: str) -> List[Violation]:
    out: List[Violation] = []
    for sf in load_files(root, package_files(root)):
        sf._lint_mutable_globals = module_mutable_globals(  # type: ignore
            sf.tree)
        seen = set()
        for fn, jit_call, report in _jit_targets(sf):
            key = (id(fn), getattr(report, "lineno", 0))
            if key in seen:
                continue
            seen.add(key)
            out.extend(_check_target(sf, fn, jit_call, report))
    return out
