"""gate-lint: OFF-by-default subsystems must follow the None-returning
scope-gate pattern — the no-op discipline bench.py asserts dynamically,
promoted to a static check.

The contract (PR 4 tracer, PR 6 fault injector, PR 7 transfer ledger,
PR 8 sync sanitizer, PR 10 flight recorder): a subsystem that is OFF by
default costs the hot path ONE attribute load and a branch. Statically
that means:

1. the flag defaults to False — `self.enabled = False` in __init__ (or
   a module-level `ENABLED = False` for the faults-style module gate);
2. every registered gate method tests the flag and returns a constant
   no-op value (None / NOOP_SPAN / a plain return) on the disabled
   branch — callers guard with `if x is not None`, nothing allocates;
3. module-flag subsystems are guarded at the CALL SITE: every
   `faults.fire(...)` in the package must sit lexically under an `if`
   that tests `faults.ENABLED` (the disabled path must never enter the
   function at all).

The registry below is the list of gated subsystems; adding a subsystem
means adding a row, and the checker fails loudly if a registered
module/class/method disappears (a silently-unchecked gate is how the
discipline rots).
"""

from __future__ import annotations

import ast
from typing import List, Optional

from .core import (SourceFile, Violation, load_files, name_of,
                   package_files)

RULE = "gate-lint"

# (file, class or None for module-level, flag name, gate methods)
GATED_SUBSYSTEMS = (
    ("opensearch_tpu/telemetry/tracer.py", "Tracer", "enabled",
     ("start_trace",)),
    ("opensearch_tpu/telemetry/ledger.py", "TransferLedger", "enabled",
     ("scope", "new_wave")),
    ("opensearch_tpu/common/faults.py", None, "ENABLED", ()),
    ("opensearch_tpu/common/sanitize.py", "SyncSanitizer", "enabled",
     ("check",)),
    ("opensearch_tpu/telemetry/lifecycle.py", "FlightRecorder", "enabled",
     ("timeline",)),
    # ISSUE 11 admission stages: every adaptive stage of the admission
    # pipeline (quota -> breaker -> deadline shed) is OFF by default —
    # the default node keeps the static permit gate exactly
    ("opensearch_tpu/common/admission.py", "TenantQuotas", "enabled",
     ("gate",)),
    ("opensearch_tpu/common/admission.py", "DeadlineShedder", "enabled",
     ("gate",)),
    ("opensearch_tpu/common/admission.py", "DeviceMemoryBreaker",
     "enabled", ("gate",)),
    # ISSUE 12 wave scheduler: the cross-request coalescing layer is
    # OFF by default — the default node executes every search inline,
    # exactly the pre-scheduler path
    ("opensearch_tpu/search/scheduler.py", "WaveScheduler", "enabled",
     ("gate",)),
    # ISSUE 13 write-path observability: the ingest lifecycle recorder
    # and the segment-churn ledger are OFF by default — the default
    # write path pays one attribute load + branch per op (timeline/
    # current) and per refresh (scope/current)
    ("opensearch_tpu/telemetry/lifecycle.py", "IngestRecorder",
     "enabled", ("timeline", "current")),
    ("opensearch_tpu/telemetry/ledger.py", "ChurnLedger", "enabled",
     ("scope", "current")),
    # ISSUE 14 sharded-serving observability: the per-device ledger
    # (per-chip transfer/phase attribution + straggler skew) and the
    # SPMD collective-phase timeline emitter are OFF by default — the
    # default SPMD query path pays one attribute load + branch per
    # query for each. (The scan counters are deliberately ALWAYS-ON —
    # the block-max trigger metric rides the inflight-wave-gauge
    # contract, not the per-request gate discipline.)
    ("opensearch_tpu/telemetry/ledger.py", "DeviceLedger", "enabled",
     ("scope",)),
    ("opensearch_tpu/telemetry/lifecycle.py", "SpmdTimeline", "enabled",
     ("gate",)),
    # ISSUE 15 query insights: the per-shape cost recorder is OFF by
    # default — the default query path pays one attribute load + branch
    # per sub-request — and the shape-aware deadline-shed pricing is a
    # SECOND gate on the shedder (its own flag on top of `enabled`):
    # the default shed stage never computes a shape key at admission
    ("opensearch_tpu/telemetry/insights.py", "QueryInsights", "enabled",
     ("gate",)),
    ("opensearch_tpu/common/admission.py", "DeadlineShedder",
     "shape_enabled", ("shape_gate",)),
    # ISSUE 16 ingest-concurrent serving: every fix is OFF by default —
    # the default node keeps the r01 write path exactly. Precompiler:
    # None-returning gate; memo carry / windowed merge: plain False
    # flags branched at their single call site (stats rebuild / merge
    # dispatch); delta publish: faults-style module flag branched in
    # publish_segment.
    ("opensearch_tpu/search/warmup.py", "Precompiler", "enabled",
     ("gate",)),
    # barrier mode is a SECOND gate on the precompiler (shape_enabled
    # idiom): stage-and-replay-before-publish only runs when both flags
    # are on — the default publish stays the direct atomic swap
    ("opensearch_tpu/search/warmup.py", "Precompiler", "barrier", ()),
    ("opensearch_tpu/search/executor.py", "ShardReader", "memo_carry",
     ()),
    ("opensearch_tpu/index/engine.py", "InternalEngine",
     "merge_windowed", ()),
    ("opensearch_tpu/ops/device_segment.py", None, "DELTA_PUBLISH", ()),
    # single-round-trip result page (ISSUE 17): OFF by default — the
    # legacy multi-channel collect is the pristine path
    ("opensearch_tpu/search/executor.py", None, "RESULT_PAGE", ()),
    # ISSUE 18 late-interaction rerank: the device-scoring arm of
    # rescore_maxsim is OFF by default — the pristine rerank path is
    # the host numpy mirror (same f32 math, no device dispatch)
    ("opensearch_tpu/searchpipeline/processors.py", None,
     "MAXSIM_DEVICE_RESCORE", ()),
    # ISSUE 19 kernel profiler: the sampled-dispatch timer is OFF by
    # default behind a None-returning gate() — disabled, executables
    # return UNWRAPPED (no timer closure); the executable census is
    # always-on but writes only at compile time (never on the steady
    # state), the inflight-wave-gauge contract, not this discipline
    ("opensearch_tpu/telemetry/kernels.py", "KernelProfiler", "enabled",
     ("gate",)),
    # ISSUE 20 block-max pruning: OFF by default — the pristine query
    # path compiles no tid/bscale inputs and masks nothing; the seal-
    # time bounds leaf is always present (upload cost, not query cost)
    # so flipping the gate never re-uploads segments
    ("opensearch_tpu/ops/bm25.py", None, "BLOCKMAX", ()),
)

# no-op constants a disabled gate may return
NOOP_NAMES = {"NOOP_SPAN", "None"}


def _find_class(tree: ast.Module, name: str) -> Optional[ast.ClassDef]:
    for stmt in tree.body:
        if isinstance(stmt, ast.ClassDef) and stmt.name == name:
            return stmt
    return None


def _method(cls: ast.ClassDef, name: str) -> Optional[ast.FunctionDef]:
    for stmt in cls.body:
        if isinstance(stmt, ast.FunctionDef) and stmt.name == name:
            return stmt
    return None


def _mentions_flag(node: ast.AST, flag: str) -> bool:
    for n in ast.walk(node):
        if isinstance(n, ast.Attribute) and n.attr == flag:
            return True
        if isinstance(n, ast.Name) and n.id == flag:
            return True
    return False


def _init_defaults_false(cls: ast.ClassDef, flag: str) -> bool:
    init = _method(cls, "__init__")
    if init is None:
        # class-level default (`enabled = False`) is acceptable
        for stmt in cls.body:
            if isinstance(stmt, ast.Assign):
                for t in stmt.targets:
                    if isinstance(t, ast.Name) and t.id == flag:
                        return isinstance(stmt.value, ast.Constant) and \
                            stmt.value.value is False
        return False
    for node in ast.walk(init):
        if isinstance(node, ast.Assign):
            for t in node.targets:
                if isinstance(t, ast.Attribute) and t.attr == flag and \
                        isinstance(t.value, ast.Name) and \
                        t.value.id == "self":
                    return isinstance(node.value, ast.Constant) and \
                        node.value.value is False
    # fall back to class-level default
    for stmt in cls.body:
        if isinstance(stmt, ast.Assign):
            for t in stmt.targets:
                if isinstance(t, ast.Name) and t.id == flag:
                    return isinstance(stmt.value, ast.Constant) and \
                        stmt.value.value is False
    return False


def _module_flag_false(tree: ast.Module, flag: str) -> bool:
    for stmt in tree.body:
        if isinstance(stmt, ast.Assign):
            for t in stmt.targets:
                if isinstance(t, ast.Name) and t.id == flag:
                    return isinstance(stmt.value, ast.Constant) and \
                        stmt.value.value is False
    return False


def _gate_ok(fn: ast.FunctionDef, flag: str) -> bool:
    """The method tests the flag AND has a no-op return (None constant,
    a NOOP_* name, or a bare `return`) reachable for the disabled case."""
    has_guard = any(isinstance(n, (ast.If, ast.IfExp)) and
                    _mentions_flag(n.test, flag)
                    for n in ast.walk(fn))
    if not has_guard:
        return False
    for n in ast.walk(fn):
        if isinstance(n, ast.Return):
            v = n.value
            if v is None:
                return True
            if isinstance(v, ast.Constant) and v.value is None:
                return True
            if isinstance(v, ast.Name) and (v.id in NOOP_NAMES or
                                            v.id.startswith("NOOP")):
                return True
    return False


def run(root: str) -> List[Violation]:
    out: List[Violation] = []
    by_rel = {}

    def _load(rel: str) -> Optional[SourceFile]:
        if rel not in by_rel:
            files = load_files(root, [rel])
            by_rel[rel] = files[0] if files else None
        return by_rel[rel]

    for rel, cls_name, flag, gates in GATED_SUBSYSTEMS:
        sf = _load(rel)
        if sf is None:
            out.append(Violation(RULE, rel, 1,
                                 "registered gated subsystem file is "
                                 "missing"))
            continue
        if cls_name is None:
            if not _module_flag_false(sf.tree, flag):
                out.append(Violation(
                    RULE, rel, 1,
                    f"module gate flag [{flag}] must default to a "
                    f"literal False"))
            continue
        cls = _find_class(sf.tree, cls_name)
        if cls is None:
            out.append(Violation(RULE, rel, 1,
                                 f"registered gated class [{cls_name}] "
                                 f"not found"))
            continue
        if not _init_defaults_false(cls, flag):
            out.append(Violation(
                RULE, rel, cls.lineno,
                f"{cls_name}.{flag} must be initialized to a literal "
                f"False (OFF by default)"))
        for gate in gates:
            m = _method(cls, gate)
            if m is None:
                out.append(Violation(
                    RULE, rel, cls.lineno,
                    f"registered gate method {cls_name}.{gate}() not "
                    f"found"))
                continue
            if not _gate_ok(m, flag):
                out.append(Violation(
                    RULE, rel, m.lineno,
                    f"{cls_name}.{gate}() must test [{flag}] and return "
                    f"a no-op constant (None / NOOP_*) on the disabled "
                    f"branch"))

    # call-site guard for the module-flag subsystem: faults.fire()
    for sf in load_files(root, package_files(root)):
        if sf.rel.endswith("common/faults.py"):
            continue
        for node in ast.walk(sf.tree):
            if not isinstance(node, ast.Call):
                continue
            callee = name_of(node.func)
            if callee not in ("faults.fire", "fire"):
                continue
            if callee == "fire" and "faults" not in sf.text:
                continue
            guarded = any(
                isinstance(anc, ast.If) and
                _mentions_flag(anc.test, "ENABLED")
                for anc in sf.ancestors(node))
            if not guarded:
                # early-return form: an enclosing function that bails
                # out first (`if not faults.ENABLED: return ...`) guards
                # every statement after it, nested closures included
                for fn in sf.enclosing_functions(node):
                    if isinstance(fn, ast.Lambda):
                        continue
                    for stmt in fn.body:
                        if getattr(stmt, "lineno", 1 << 30) >= node.lineno:
                            break
                        if isinstance(stmt, ast.If) and \
                                _mentions_flag(stmt.test, "ENABLED") and \
                                any(isinstance(s, ast.Return)
                                    for s in ast.walk(stmt)):
                            guarded = True
                            break
                    if guarded:
                        break
            if not guarded:
                out.append(Violation(
                    RULE, sf.rel, node.lineno,
                    "faults.fire() must sit under `if faults.ENABLED:` "
                    "— the disabled hot path must not enter the call"))
    return out
