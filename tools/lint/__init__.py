"""Repo-specific static-analysis suite (ISSUE 8).

Four AST-based checkers enforce the invariants the ROADMAP item-1/item-2
rewrites (on-device top-k + overlapped transfers, async wave scheduler)
depend on — invariants that were previously enforced by convention and
re-verified only dynamically (bench.py's no-op asserts):

- sync-lint          every host<->device sync site on the query path is
                     ledger-attributed or carries `# sync-ok: <channel>`
                     (+ the exception-breadth rule: no blanket
                     `except Exception` without `# except-ok: <reason>`)
- retrace-lint       jitted functions can't close over mutable module
                     globals, branch on tracer values, or call
                     shape-data-dependent ops
- gate-lint          OFF-by-default subsystems (tracer, fault injector,
                     transfer ledger, sync sanitizer) follow the
                     None-returning scope-gate pattern
- shared-state-lint  module-level mutable state mutated on the query
                     path must be lock-guarded, registry-owned, or
                     annotated `# shared-state-ok: <reason>`

Run via `python tools/lint.py` (or `python -m lint` with tools/ on the
path). The runtime counterpart is `opensearch_tpu/common/sanitize.py`.
"""

from .core import RULE_BITS, Violation, repo_root  # noqa: F401
from .runner import main, run_all  # noqa: F401
