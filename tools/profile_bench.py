"""Per-phase profile of bench config 1 (BM25 match msearch batch).

Round-3 verdict demanded a committed breakdown of where the 1.5s msearch
batch goes: host prep (parse/compile/pad) vs device dispatch vs device
compute vs transfer — plus microbenchmarks of the kernel's building blocks
(gather+BM25, dense scatter-add, full-width top_k, and the candidate-buffer
alternative) at the measured shapes, so the optimization attacks the real
bottleneck. Writes PROFILE.md at the repo root.

Usage:  python tools/profile_bench.py  [BENCH_DOCS=100000 BENCH_QUERIES=1024]
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

RESULTS: list = []


def log(name, seconds, note=""):
    RESULTS.append((name, seconds, note))
    print(f"{name:44s} {seconds * 1000:10.1f} ms  {note}", flush=True)


def main():
    os.environ.setdefault("BENCH_PROBE_TIMEOUTS", "300,120")
    import bench
    bench.ensure_backend()
    import jax
    import jax.numpy as jnp

    platform = jax.devices()[0].platform
    print(f"platform: {platform}")

    from opensearch_tpu.utils.demo import query_terms

    t0 = time.perf_counter()
    executor, seg = bench.build_index()
    log("index build (host)", time.perf_counter() - t0)

    queries = query_terms(bench.N_QUERIES, bench.VOCAB, seed=7,
                          terms_per_query=2)
    bodies = [{"query": {"match": {"body": q}}, "size": bench.TOP_K}
              for q in queries]

    # ---- end-to-end: warm + timed run (what bench.py measures)
    t0 = time.perf_counter()
    executor.multi_search(bodies)
    log("msearch cold (compiles)", time.perf_counter() - t0)
    from opensearch_tpu.telemetry import TELEMETRY
    TELEMETRY.metrics.reset()
    # ledger ON for the whole profile: the per-stage timings below are
    # taken via ledger-attributed device_get (the only true sync on the
    # tunnel), so the run's channel/wave decomposition is real data
    TELEMETRY.ledger.enabled = True
    TELEMETRY.ledger.reset()
    t0 = time.perf_counter()
    executor.multi_search(bodies)
    total = time.perf_counter() - t0
    log("msearch warm TOTAL", total,
        f"{len(bodies) / total:.0f} QPS")
    snap = TELEMETRY.metrics.to_dict()
    for name, h in sorted(snap["histograms"].items()):
        if name.startswith("msearch.phase."):
            log(f"warm phase: {name[len('msearch.phase.'):-len('_ms')]}",
                h["sum_ms"] / 1000)
    print("interning counters:",
          {k: v for k, v in snap["counters"].items()
           if "template" in k or k == "search.plan_compiles"})

    # ---- dissect the warm path (mirrors multi_search's envelope path)
    from opensearch_tpu.search import dsl
    from opensearch_tpu.search.compile import Compiler
    from opensearch_tpu.search.executor import (_envelope_runner,
                                                pack_leaves,
                                                stack_flat_inputs)
    from opensearch_tpu.index.segment import pad_bucket
    from opensearch_tpu.parallel.distributed import (_tree_shapes,
                                                     plan_struct)

    t0 = time.perf_counter()
    stats = executor.reader.stats()
    compiler = Compiler(executor.reader.mapper, stats)
    compiled = []
    for body in bodies:
        node = dsl.parse_query(body["query"])
        compiled.append(compiler.compile(
            node, executor.reader.segments[0], executor.reader.device[0][1]))
    log("host: parse+compile plans", time.perf_counter() - t0,
        f"{len(bodies)} plans")

    t0 = time.perf_counter()
    flats_all = [p.flatten_inputs([]) for p in compiled]
    groups = {}
    for i, p in enumerate(compiled):
        groups.setdefault((plan_struct(p), _tree_shapes(flats_all[i])),
                          []).append(i)
    log("host: flatten+group", time.perf_counter() - t0,
        f"{len(groups)} group(s)")

    arrays, meta = executor.reader.device[0]
    group_stats = []
    t_stack = t_pack = t_upload = t_disp = 0.0
    pending = []
    for (struct, shapes), idxs in groups.items():
        b_pad = pad_bucket(len(idxs), minimum=1)
        t0 = time.perf_counter()
        group_flats = [flats_all[i] for i in idxs]
        group_flats += [group_flats[0]] * (b_pad - len(idxs))
        stacked, treedef, _axes = stack_flat_inputs(group_flats)
        stacked.append(np.full(b_pad, -1e38, np.float32))
        t_stack += time.perf_counter() - t0
        t0 = time.perf_counter()
        buf, layout = pack_leaves(stacked)
        t_pack += time.perf_counter() - t0
        t0 = time.perf_counter()
        dev_buf = jnp.asarray(buf)
        t_upload += time.perf_counter() - t0
        plan0 = compiled[idxs[0]]
        fn = _envelope_runner(plan_struct(plan0), plan0, meta, 10,
                              layout, treedef)
        t0 = time.perf_counter()
        pending.append(fn(arrays, dev_buf))
        t_disp += time.perf_counter() - t0
        group_stats.append((len(idxs), b_pad, buf.nbytes))
    log("host: stack", t_stack)
    log("host: pack envelope", t_pack)
    log("host: upload (asarray calls)", t_upload,
        f"{sum(g[2] for g in group_stats)} B")
    log("host: dispatch (async calls)", t_disp)
    # Stage boundary measured via a LEDGER-ATTRIBUTED device_get — the
    # only true sync point on the tunnel. The old two-stage split
    # ("block_until_ready" then "device_get") under-measured: on the
    # tunneled device block_until_ready can return WITHOUT a round trip,
    # so its stage read near-zero while the next stage silently absorbed
    # the execute wall (PROFILE.md round 10 documents the fix). One
    # attributed fetch charges execute + transfer to one honest number,
    # and the ledger records it like any serving-path collect.
    ledger = TELEMETRY.ledger
    t0 = time.perf_counter()
    with ledger.attributed():
        fetched = jax.device_get(pending)
    collect_s = time.perf_counter() - t0
    fetched_b = sum(np.asarray(f).nbytes for f in fetched)
    ledger.note_device_get(collect_s * 1000, nbytes=fetched_b)
    log("device+transfer: attributed device_get", collect_s,
        f"{fetched_b} B (execute+fetch; block_until_ready is not a "
        f"tunnel barrier)")

    d_pad = int(arrays["live"].shape[0])
    b_total = sum(b for b, _, _ in group_stats)
    qb_max = 0
    for (struct, shapes), _ in groups.items():
        for _, shp, _dt in shapes:
            if len(shp) == 1:
                qb_max = max(qb_max, shp[0])
    print(f"\ngroups (n, b_pad, bytes): {group_stats}  d_pad={d_pad} "
          f"qb_max={qb_max}")

    # ---- microbenchmarks at representative shapes
    B = min(b_total, 1024)
    QB = max(qb_max, 16)
    rng = np.random.RandomState(0)
    ids = jnp.asarray(rng.randint(0, arrays["post_docs"].shape[0],
                                  size=(B, QB)), dtype=jnp.int32)
    w = jnp.asarray(rng.rand(B, QB), dtype=jnp.float32)

    def timed(fn, *args, reps=3, name="", note=""):
        """Microbench via ledger-attributed device_get, NOT
        block_until_ready: on the tunnel only device_get forces the
        round trip, so block_until_ready-timed stages read fast while
        the wall silently moves to whoever syncs next (the round-4
        follow-up's caveat, fixed here — PROFILE.md round 10)."""
        out = fn(*args)
        with ledger.attributed():
            jax.device_get(out)                 # warm (compile) pass
            t0 = time.perf_counter()
            for _ in range(reps):
                out = fn(*args)
                jax.device_get(out)
            dt = (time.perf_counter() - t0) / reps
        ledger.note_device_get(dt * 1000)
        log(name, dt, note)

    post_docs, post_tf = arrays["post_docs"], arrays["post_tf"]

    @jax.jit
    def k_gather(ids, w):
        docs = post_docs[ids]                       # [B, QB, 128]
        tfs = post_tf[ids]
        part = w[:, :, None] * tfs / (tfs + 1.2)
        return part.sum(axis=(1, 2))

    timed(k_gather, ids, w, name="μ: gather+bm25 (no scatter)",
          note=f"[B={B},QB={QB},128]")

    @jax.jit
    def k_scatter(ids, w):
        docs = post_docs[ids]
        tfs = post_tf[ids]
        part = w[:, :, None] * tfs / (tfs + 1.2)
        valid = docs >= 0
        sidx = jnp.where(valid, docs, d_pad)

        def one(s, p):
            return jnp.zeros(d_pad, jnp.float32).at[s.ravel()].add(
                p.ravel(), mode="drop")
        return jax.vmap(one)(sidx, jnp.where(valid, part, 0.0))

    timed(k_scatter, ids, w, name="μ: + dense scatter [B,d_pad]",
          note=f"out {B}x{d_pad}")

    @jax.jit
    def k_scatter_topk(ids, w):
        dense = k_scatter(ids, w)
        return jax.lax.top_k(dense, 10)

    timed(k_scatter_topk, ids, w, name="μ: + full-width top_k(10)")

    @jax.jit
    def k_scatter_topk2(ids, w):
        dense = k_scatter(ids, w)
        rows = dense.reshape(B, d_pad // 128, 128)
        part_v, part_i = jax.lax.top_k(rows, 10)        # [B, R, 10]
        base = (jnp.arange(d_pad // 128) * 128)[None, :, None]
        flat_v = part_v.reshape(B, -1)
        flat_i = (part_i + base).reshape(B, -1)
        v, j = jax.lax.top_k(flat_v, 10)
        return v, jnp.take_along_axis(flat_i, j, axis=1)

    timed(k_scatter_topk2, ids, w, name="μ: + two-stage top_k(10)")

    # candidate-buffer alternative: sort postings lanes by doc id,
    # segment-sum duplicates, top-k over the compact buffer
    @jax.jit
    def k_candidates(ids, w):
        docs = post_docs[ids].reshape(B, -1)            # [B, N]
        tfs = post_tf[ids].reshape(B, -1)
        part = jnp.where(docs >= 0,
                         w.repeat(128, axis=1) * tfs / (tfs + 1.2), 0.0)
        big = jnp.where(docs >= 0, docs, 2 ** 30)
        sdocs, spart = jax.lax.sort([big, part], num_keys=1)
        csum = jnp.cumsum(spart, axis=1)
        n = sdocs.shape[1]
        last = jnp.concatenate([sdocs[:, :-1] != sdocs[:, 1:],
                                jnp.ones((B, 1), bool)], axis=1)
        run_total = jnp.where(
            last, csum - jnp.concatenate(
                [jnp.zeros((B, 1), jnp.float32),
                 jnp.where(last, csum, 0.0)[:, :-1]], axis=1), 0.0)
        # (approx for the μbench: mask non-run-ends, topk over N)
        masked = jnp.where(last & (sdocs < 2 ** 30), csum, -1e38)
        v, j = jax.lax.top_k(masked, 10)
        return v, jnp.take_along_axis(sdocs, j, axis=1)

    timed(k_candidates, ids, w,
          name="μ: candidate-buffer (sort+segsum+topk)",
          note=f"N={QB * 128}")

    # raw run dump goes to PROFILE_RUN.md — PROFILE.md is the curated
    # analysis and must not be clobbered by a (possibly tunnel-degraded)
    # ad-hoc run; tunnel RT varies 66-600ms between sessions
    lsnap = TELEMETRY.ledger.snapshot()
    with open(os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), "PROFILE_RUN.md"), "w") as f:
        f.write("# bench config 1 profile run (%s)\n\n" % platform)
        f.write("All device-stage timings are ledger-attributed "
                "`device_get` walls — `block_until_ready` is NOT a "
                "sync barrier on the tunnel and under-measures "
                "(PROFILE.md round 10).\n\n")
        f.write("| phase | ms | note |\n|---|---|---|\n")
        for name, sec, note in RESULTS:
            f.write(f"| {name} | {sec * 1000:.1f} | {note} |\n")
        f.write(f"\ngroups (n, b_pad, bytes): {group_stats}; "
                f"d_pad={d_pad}; qb_max={qb_max}; B={B}\n")
        f.write(f"\nledger: waves={lsnap['waves']} "
                f"device_get={lsnap['device_get']} "
                f"pipeline={lsnap['pipeline']}\n")
    print("\nwrote PROFILE_RUN.md")


if __name__ == "__main__":
    main()
