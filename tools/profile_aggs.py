"""Per-phase profile of the agg bench configs (2/3: agg_terms, date_hist).

Round-6 counterpart of profile_bench.py for the aggregation path: runs the
bench workload through the msearch envelope, reports the telemetry
`msearch.phase.*` histograms per config plus an ablation (query-only / each agg alone / both), and times
the executable-warmup subsystem (cold compile vs post-warmup replay).
Writes PROFILE_AGGS_RUN.md; PROFILE.md holds the curated analysis.

Usage: python tools/profile_aggs.py   [BENCH_DOCS=50000 BENCH_AGG_QUERIES=32]
"""

from __future__ import annotations

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

RESULTS: list = []


def log(name, ms, note=""):
    RESULTS.append((name, ms, note))
    print(f"{name:42s} {ms:9.1f} ms  {note}", flush=True)


def main():
    os.environ.setdefault("BENCH_DOCS", "50000")
    import bench
    bench.ensure_backend()
    import jax

    platform = jax.devices()[0].platform
    print(f"platform: {platform}")
    executor, seg = bench.build_index()
    n_q = int(os.environ.get("BENCH_AGG_QUERIES", "32"))
    rng = np.random.RandomState(13)
    day = 86400_000
    spans = 1 + 79 * rng.permutation(n_q) / max(n_q, 1)

    from opensearch_tpu.indices.request_cache import REQUEST_CACHE
    from opensearch_tpu.telemetry import TELEMETRY

    def q(s):
        return {"range": {"ts": {"lt": int(1700000000000 + s * day)}}}

    def run(tag, mk_body, reps=5):
        bodies = [mk_body(s) for s in spans]
        t0 = time.perf_counter()
        executor.multi_search(bodies)
        cold = (time.perf_counter() - t0) * 1000
        TELEMETRY.metrics.reset()
        times = []
        for _ in range(reps):
            REQUEST_CACHE.clear()
            t0 = time.perf_counter()
            executor.multi_search(bodies)
            times.append((time.perf_counter() - t0) * 1000)
        med = sorted(times)[reps // 2]
        hists = TELEMETRY.metrics.to_dict()["histograms"]
        ph = {name[len("msearch.phase."):-len("_ms")]:
              round(h["sum_ms"] / reps, 2)
              for name, h in sorted(hists.items())
              if name.startswith("msearch.phase.")}
        log(f"{tag}: warm batch median", med, f"cold={cold:.0f}ms B={n_q}")
        for k, v in ph.items():
            log(f"{tag}:   phase {k}", v)
        return med

    dh = {"per_day": {"date_histogram": {"field": "ts",
                                         "fixed_interval": "1d"}}}
    cd = {"uniq": {"cardinality": {"field": "tag"}}}
    run("query-only", lambda s: {"size": 0, "query": q(s)})
    run("date_hist", lambda s: {"size": 0, "query": q(s), "aggs": dh})
    run("cardinality", lambda s: {"size": 0, "query": q(s), "aggs": cd})
    run("both", lambda s: {"size": 0, "query": q(s), "aggs": {**dh, **cd}})

    # warmup subsystem: cold-compile cost vs post-warmup replay of the
    # registered (plan-struct, shape-bucket) executables
    from opensearch_tpu.search import executor as ex_mod
    from opensearch_tpu.search.warmup import WARMUP
    n_reg = WARMUP.stats()["registered"]
    ex_mod._JIT_CACHE.clear()
    t0 = time.perf_counter()
    r = WARMUP.warm_executor(executor)
    log("warmup: replay after executable-cache wipe",
        (time.perf_counter() - t0) * 1000,
        f"{r['warmed']} entries of {n_reg} registered")
    t0 = time.perf_counter()
    r = WARMUP.warm_executor(executor)
    log("warmup: second replay (all compiled)",
        (time.perf_counter() - t0) * 1000, f"{r['warmed']} entries")

    out = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "PROFILE_AGGS_RUN.md")
    with open(out, "w") as f:
        f.write(f"# agg bench profile run ({platform})\n\n")
        f.write("| phase | ms | note |\n|---|---|---|\n")
        for name, ms, note in RESULTS:
            f.write(f"| {name} | {ms:.1f} | {note} |\n")
    print("\nwrote PROFILE_AGGS_RUN.md")


if __name__ == "__main__":
    main()
