"""Host-cost profile of the msearch envelope across batch sizes.

ISSUE 5 tooling: sweeps B ∈ {1, 32, 1024} (configurable) over the bench's
BM25 match workload and prints the per-phase host breakdown from the
always-on telemetry histograms (`msearch.phase.*`), plus the
template-interning counters — so "compile+group is O(unique templates),
not O(B)" is a number you can watch, not a claim.

Each sweep point runs the batch once COLD (executable + skeleton compile)
and `rounds` times WARM with metrics reset in between; the warm rows are
what steady-state serving pays. The returned dict is consumed by the
tier-1 smoke test (tests/test_profile_host.py) on a tiny corpus, which
asserts the interning counters move the right way (bundle hits on warm
batches; zero plan/XLA compiles on a repeated identical batch).

Usage:  python tools/profile_host.py
        BENCH_DOCS=100000 BENCH_VOCAB=20000 python tools/profile_host.py
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import time

PHASE_ORDER = ("parse", "compile_group", "stack_pack_dispatch",
               "device_get", "respond")

COUNTERS = ("msearch.template.bundle_hits",
            "msearch.template.bundle_misses",
            "msearch.template.fallbacks",
            "search.template_binds",
            "search.plan_compiles",
            "search.xla_cache_miss")


def run_sweep(n_docs: int = 100_000, vocab: int = 20_000,
              batches=(1, 32, 1024), rounds: int = 3, top_k: int = 10,
              quiet: bool = False) -> dict:
    from opensearch_tpu.search.executor import SearchExecutor, ShardReader
    from opensearch_tpu.telemetry import TELEMETRY
    from opensearch_tpu.utils.demo import build_shards, query_terms

    mapper, segments = build_shards(n_docs, n_shards=1, vocab_size=vocab,
                                    avg_len=60, seed=42)
    executor = SearchExecutor(ShardReader(mapper, segments))

    def emit(line=""):
        if not quiet:
            print(line, flush=True)

    results = {}
    max_b = max(batches)
    queries = query_terms(max_b, vocab, seed=7, terms_per_query=2)
    for b in batches:
        bodies = [{"query": {"match": {"body": q}}, "size": top_k}
                  for q in queries[:b]]
        t0 = time.perf_counter()
        executor.multi_search(bodies)               # cold: compiles
        cold_ms = (time.perf_counter() - t0) * 1000
        TELEMETRY.metrics.reset()
        warm_ms = []
        for _ in range(rounds):
            t0 = time.perf_counter()
            executor.multi_search(bodies)
            warm_ms.append((time.perf_counter() - t0) * 1000)
        snap = TELEMETRY.metrics.to_dict()
        phases = {name[len("msearch.phase."):-len("_ms")]:
                  h["sum_ms"] / rounds
                  for name, h in snap["histograms"].items()
                  if name.startswith("msearch.phase.")}
        counters = {c: snap["counters"].get(c, 0) for c in COUNTERS}
        results[b] = {"cold_ms": cold_ms,
                      "warm_ms": sorted(warm_ms)[len(warm_ms) // 2],
                      "phases": phases, "counters": counters}
        emit(f"B={b:5d}  cold {cold_ms:8.1f} ms   warm "
             f"{results[b]['warm_ms']:8.1f} ms "
             f"({b / (results[b]['warm_ms'] / 1000):.0f} QPS)")
        for name in PHASE_ORDER:
            emit(f"    phase {name:20s} {phases.get(name, 0.0):8.2f} ms"
                 f"/batch")
        emit(f"    counters ({rounds} warm rounds): "
             + "  ".join(f"{c.split('.')[-1]}={counters[c]}"
                         for c in COUNTERS))
        emit()
    return results


def main():
    n_docs = int(os.environ.get("BENCH_DOCS", "100000"))
    vocab = int(os.environ.get("BENCH_VOCAB", "20000"))
    batches = tuple(int(x) for x in os.environ.get(
        "PROFILE_HOST_BATCHES", "1,32,1024").split(","))
    print(f"profile_host: docs={n_docs} vocab={vocab} batches={batches}")
    run_sweep(n_docs=n_docs, vocab=vocab, batches=batches)


if __name__ == "__main__":
    main()
