#!/usr/bin/env python
"""Per-phase latency report from a telemetry trace dump.

The successor to the ad-hoc profiling runs in PROFILE.md: instead of
hand-instrumented one-off scripts, point this at the tracer's output and
get the per-phase latency distribution of real traffic.

Input (auto-detected), any of:
  - the JSONL export the node appends under `<data>/_state/traces.jsonl`
    (one {"trace": {...}, "ts_ms": N} object per line);
  - a saved `GET /_telemetry/traces` response ({"traces": [...]});
  - a bare JSON array of trace records.

Output: one fixed-width table — per phase (root spans' direct children,
grouped by span name) count, p50/p99/max milliseconds and share of total
root time — plus the root-span latency line. Pure stdlib; no server
required.

    python tools/trace_report.py data/_state/traces.jsonl
    curl -s localhost:9200/_telemetry/traces | python tools/trace_report.py -
"""

from __future__ import annotations

import json
import sys
from typing import Any, Dict, List


def _extract_trace(obj: Any) -> Any:
    """A record may be the span dict itself or wrapped as {"trace": ...}."""
    if isinstance(obj, dict) and "trace" in obj and "name" not in obj:
        return obj["trace"]
    return obj


def load_traces(path: str) -> List[dict]:
    """Parse a trace dump file ('-' = stdin) into root-span dicts."""
    text = sys.stdin.read() if path == "-" else open(path).read()
    text = text.strip()
    if not text:
        return []
    traces: List[Any] = []
    if text[0] == "{" and "\n" in text:
        # try JSONL first — skipping corrupt/truncated lines (a node
        # killed mid-append leaves one): the valid traces still report
        parsed, bad = [], 0
        for line in text.splitlines():
            if not line.strip():
                continue
            try:
                parsed.append(json.loads(line))
            except json.JSONDecodeError:
                bad += 1
        if parsed and (len(parsed) > 1 or bad):
            if bad:
                print(f"warning: skipped {bad} unparseable line(s)",
                      file=sys.stderr)
            traces = parsed
    if not traces:
        data = json.loads(text)
        if isinstance(data, dict):
            traces = data.get("traces", [data])
        else:
            traces = list(data)
    out = []
    for rec in traces:
        trace = _extract_trace(rec)
        if isinstance(trace, dict) and "name" in trace:
            out.append(trace)
    return out


def _pct(sorted_vals: List[float], p: float) -> float:
    if not sorted_vals:
        return 0.0
    i = min(len(sorted_vals) - 1, int(len(sorted_vals) * p))
    return sorted_vals[i]


def phase_rows(traces: List[dict]) -> List[dict]:
    """Group root spans' direct children by name; one stats row each."""
    per_phase: Dict[str, List[float]] = {}
    roots: List[float] = []
    for trace in traces:
        roots.append(float(trace.get("duration_ms", 0.0)))
        for child in trace.get("children") or []:
            per_phase.setdefault(child.get("name", "?"), []).append(
                float(child.get("duration_ms", 0.0)))
    total_root = sum(roots) or 1.0
    rows = []
    for name in sorted(per_phase):
        vals = sorted(per_phase[name])
        rows.append({
            "phase": name,
            "count": len(vals),
            "p50_ms": round(_pct(vals, 0.5), 3),
            "p99_ms": round(_pct(vals, 0.99), 3),
            "max_ms": round(vals[-1], 3),
            "total_ms": round(sum(vals), 3),
            "pct_of_root": round(100.0 * sum(vals) / total_root, 1),
        })
    roots.sort()
    rows.append({
        "phase": "(root)",
        "count": len(roots),
        "p50_ms": round(_pct(roots, 0.5), 3),
        "p99_ms": round(_pct(roots, 0.99), 3),
        "max_ms": round(roots[-1], 3) if roots else 0.0,
        "total_ms": round(sum(roots), 3),
        "pct_of_root": 100.0,
    })
    return rows


def pipeline_rows(traces: List[dict]) -> List[dict]:
    """Per-wave pipeline attribution (the PR 9 `pipeline` fields): one
    row per (trace, wave) from the span's `lifecycle` attribute
    (telemetry/lifecycle.py — coalesce/dispatch/collect/overlap events
    carry co_batched, inflight pipeline depth, per-wave overlap_ms),
    falling back to the span-level `waves`/`overlap_ms` attributes
    (LedgerScope.publish) as a single `(all)` row when no lifecycle
    rides the trace."""
    rows: List[dict] = []
    for ti, trace in enumerate(traces):
        attrs = trace.get("attributes") or {}
        lc = attrs.get("lifecycle") or {}
        # window_wait: the request's measured scheduler-queue delay
        # (lifecycle queue_wait_ms — the coalesce window's price,
        # ISSUE 12), shown on each of its wave rows
        wait = lc.get("queue_wait_ms")
        wait = wait if isinstance(wait, (int, float)) and wait > 0 \
            else "-"
        waves: Dict[Any, dict] = {}
        for ev in lc.get("events") or []:
            w = ev.get("wave")
            if w is None:
                continue
            row = waves.setdefault(w, {
                "trace": ti, "wave": w, "window_wait_ms": wait,
                "co_batched": "-", "inflight_waves": "-",
                "overlap_ms": "-", "collect_ms": "-"})
            name = ev.get("event")
            if name == "coalesce":
                row["co_batched"] = ev.get("co_batched", "-")
            elif name == "dispatch":
                row["inflight_waves"] = ev.get("inflight", "-")
            elif name == "collect":
                row["collect_ms"] = ev.get("ms", "-")
            elif name == "overlap":
                row["overlap_ms"] = ev.get("ms", "-")
        if waves:
            rows.extend(waves[w] for w in sorted(waves))
        elif "waves" in attrs or "overlap_ms" in attrs:
            rows.append({"trace": ti, "wave": "(all)",
                         "window_wait_ms": wait,
                         "co_batched": "-", "inflight_waves": "-",
                         "overlap_ms": attrs.get("overlap_ms", "-"),
                         "collect_ms": "-",
                         **({"waves": attrs["waves"]}
                            if "waves" in attrs else {})})
    return rows


def _render(rows: List[dict], headers: List[str]) -> str:
    table = [headers] + [[str(r.get(h, "-")) for h in headers]
                         for r in rows]
    widths = [max(len(row[i]) for row in table) for i in range(len(headers))]
    return "\n".join(
        "  ".join(cell.ljust(w) for cell, w in zip(row, widths)).rstrip()
        for row in table)


def render_table(rows: List[dict]) -> str:
    return _render(rows, ["phase", "count", "p50_ms", "p99_ms", "max_ms",
                          "total_ms", "pct_of_root"])


def render_pipeline_table(rows: List[dict]) -> str:
    return _render(rows, ["trace", "wave", "window_wait_ms",
                          "co_batched", "inflight_waves",
                          "overlap_ms", "collect_ms"])


def main(argv: List[str]) -> int:
    path = argv[1] if len(argv) > 1 else "-"
    traces = load_traces(path)
    if not traces:
        print("no traces found (enable tracing: "
              "POST /_telemetry/_enable, then re-run traffic)")
        return 1
    print(f"{len(traces)} trace(s)")
    print(render_table(phase_rows(traces)))
    pipe = pipeline_rows(traces)
    if pipe:
        print("\nwave pipeline (per-wave overlap / in-flight depth):")
        print(render_pipeline_table(pipe))
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv))
