#!/usr/bin/env python
""""Where did p99 go" — attribute captured slow requests' wall to named
lifecycle phases.

Input (auto-detected), any of:
  - the flight recorder's JSONL export (`<data>/_state/tail.jsonl`, or
    bench.py --clients' BENCH_CONC_TAIL_*.jsonl) — one capture record
    per line;
  - a saved `GET /_telemetry/tail` response ({"captured": [...]});
  - a bare JSON array of capture records.

Each record is one request's lifecycle timeline (telemetry/lifecycle.py)
with its ledger-fed phase decomposition. The report attributes each
capture's `took_ms` to: `queue` (queue_wait), the request's disjoint
phase set, and an `other` remainder — and prints `attr_pct`, the share
of the wall the named phases explain. The disjointness rule: when a
record carries a controller-path `query` phase, `device_get` is the
transfer ledger's SUB-attribution of `query` (shown in its own column,
not summed); on the msearch-envelope path `device_get` is its own
disjoint phase and counts.

    python tools/tail_report.py data/_state/tail.jsonl
    curl -s localhost:9200/_telemetry/tail | python tools/tail_report.py -
    python tools/tail_report.py --assert-attribution 90 BENCH_CONC_TAIL_r01.jsonl
"""

from __future__ import annotations

import json
import os
import sys
from typing import Any, Dict, List

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from trace_report import _render  # noqa: E402  (shared table renderer)

# fields riding a phase map that are not durations, plus overlap_ms
# (a measured concurrency win, not a wall slice)
NON_TIME_PHASES = frozenset({"bytes_fetched", "bytes_to_device", "waves",
                             "overlap_ms"})

# the fixed report columns; every other attributed phase folds into
# `other` so envelope- and controller-path captures share one table
COLUMNS = ("queue", "compile", "device_get", "respond", "other")

# phases bucketed as "compile" / "respond" in the fixed columns
# (`handoff` = measured response-ready → request-completed interval —
# respond-path glue + scheduler starvation under contention)
_COMPILE_PHASES = frozenset({"compile_group"})
_RESPOND_PHASES = frozenset({"respond", "render", "handoff"})


def load_records(path: str) -> List[dict]:
    """Parse a tail dump ('-' = stdin) into capture-record dicts."""
    text = sys.stdin.read() if path == "-" else open(path).read()
    text = text.strip()
    if not text:
        return []
    records: List[Any] = []
    if text[0] == "{" and "\n" in text:
        parsed, bad = [], 0
        for line in text.splitlines():
            if not line.strip():
                continue
            try:
                parsed.append(json.loads(line))
            except json.JSONDecodeError:
                bad += 1
        if parsed and (len(parsed) > 1 or bad):
            if bad:
                print(f"warning: skipped {bad} unparseable line(s)",
                      file=sys.stderr)
            records = parsed
    if not records:
        data = json.loads(text)
        if isinstance(data, dict):
            records = data.get("captured", [data])
        else:
            records = list(data)
    return [r for r in records
            if isinstance(r, dict) and "took_ms" in r]


def attribution(rec: dict) -> dict:
    """One capture's wall decomposition: per-bucket ms + attr_pct."""
    took = float(rec.get("took_ms") or 0.0)
    phases: Dict[str, float] = dict(rec.get("phases") or {})
    queue = float(rec.get("queue_wait_ms") or 0.0)
    nested_device_get = "query" in phases   # controller path: device_get
    # is the ledger's sub-attribution of the query phase
    buckets = {c: 0.0 for c in COLUMNS}
    buckets["queue"] = queue
    attributed = queue
    device_get_sub = 0.0
    for name, ms in phases.items():
        if name in NON_TIME_PHASES:
            continue
        ms = float(ms)
        if name == "device_get":
            if nested_device_get:
                device_get_sub = ms
                continue
            buckets["device_get"] += ms
        elif name in _COMPILE_PHASES:
            buckets["compile"] += ms
        elif name in _RESPOND_PHASES:
            buckets["respond"] += ms
        else:
            buckets["other"] += ms
        attributed += ms
    if nested_device_get:
        buckets["device_get"] = device_get_sub   # shown, not summed
    pct = 100.0 * attributed / took if took > 0 else 100.0
    return {
        "took_ms": round(took, 3),
        "status": rec.get("status", "?"),
        "trigger": rec.get("trigger", "?"),
        "attributed_ms": round(attributed, 3),
        "attr_pct": round(min(pct, 100.0), 1),
        "buckets": {c: round(v, 3) for c, v in buckets.items()},
        "device_get_nested": nested_device_get,
    }


def report_rows(records: List[dict]) -> List[dict]:
    rows = []
    for i, rec in enumerate(records):
        att = attribution(rec)
        row = {"capture": i, "trigger": att["trigger"],
               "took_ms": att["took_ms"]}
        for col in COLUMNS:
            v = att["buckets"][col]
            cell = f"{v:g}"
            if col == "device_get" and att["device_get_nested"]:
                cell += "*"          # sub-attribution of the query phase
            row[col] = cell
        row["attr_pct"] = att["attr_pct"]
        rows.append(row)
    return rows


def render_table(rows: List[dict]) -> str:
    return _render(rows, ["capture", "trigger", "took_ms", *COLUMNS,
                          "attr_pct"])


def coalesce_groups(records: List[dict]) -> Dict[str, dict]:
    """Group tail captures by coalesce state (ISSUE 12): a capture
    whose timeline carries any `coalesce` event with co_batched > 1
    rode a SHARED wave (cross-request companions from the scheduler, or
    envelope siblings); co_batched == 1 throughout is a solo dispatch.
    The split answers the scheduler's core tail question — are the
    slow requests the coalesced ones (window cost) or the solo ones
    (missed coalescing)? `window_wait` is the mean queue_wait of the
    group: the price the window charged its captures."""
    groups: Dict[str, dict] = {}
    for rec in records:
        cb_max = 0
        saw_wave = False
        for ev in rec.get("events") or []:
            if ev.get("event") == "coalesce":
                saw_wave = True
                cb_max = max(cb_max, int(ev.get("co_batched", 0) or 0))
        if not saw_wave:
            continue
        key = "coalesced" if cb_max > 1 else "solo"
        g = groups.setdefault(key, {
            "captures": 0, "co_batched_max": 0, "took_ms": [],
            "queue_wait_ms": []})
        g["captures"] += 1
        g["co_batched_max"] = max(g["co_batched_max"], cb_max)
        g["took_ms"].append(float(rec.get("took_ms") or 0.0))
        g["queue_wait_ms"].append(float(rec.get("queue_wait_ms") or 0.0))
    out: Dict[str, dict] = {}
    for key, g in groups.items():
        took = sorted(g["took_ms"])
        out[key] = {
            "captures": g["captures"],
            "co_batched_max": g["co_batched_max"],
            "took_p50_ms": round(took[len(took) // 2], 3),
            "took_max_ms": round(took[-1], 3),
            "window_wait_ms": round(
                sum(g["queue_wait_ms"]) / len(g["queue_wait_ms"]), 3),
        }
    return out


def render_coalesce(groups: Dict[str, dict]) -> str:
    rows = [{"state": k, **v} for k, v in sorted(groups.items())]
    return _render(rows, ["state", "captures", "co_batched_max",
                          "took_p50_ms", "took_max_ms",
                          "window_wait_ms"])


def shape_groups(records: List[dict]) -> Dict[str, dict]:
    """Group tail captures by shape class (ISSUE 15): each capture's
    `shape` annotation (the interned-template / structural-hash id the
    executor/controller stamped, the same key telemetry/insights.py
    groups costs by) answers "which shape owns the p99" the way
    `ingest_events` answers "did a merge cause it". Captures without
    the annotation (pre-ISSUE-15 dumps, rejected requests) fold into
    `_unshaped` so old files still render."""
    groups: Dict[str, dict] = {}
    annotated = False
    for rec in records:
        shape = rec.get("shape")
        if shape is not None:
            annotated = True
        key = shape if shape is not None else "_unshaped"
        g = groups.setdefault(key, {"captures": 0, "took_ms": [],
                                    "queue_wait_ms": []})
        g["captures"] += 1
        g["took_ms"].append(float(rec.get("took_ms") or 0.0))
        g["queue_wait_ms"].append(float(rec.get("queue_wait_ms") or 0.0))
    if not annotated:
        return {}
    out: Dict[str, dict] = {}
    for key, g in groups.items():
        took = sorted(g["took_ms"])
        out[key] = {
            "captures": g["captures"],
            "took_p50_ms": round(took[len(took) // 2], 3),
            "took_max_ms": round(took[-1], 3),
            "queue_wait_mean_ms": round(
                sum(g["queue_wait_ms"]) / len(g["queue_wait_ms"]), 3),
        }
    return out


def render_shapes(groups: Dict[str, dict]) -> str:
    rows = [{"shape": k, **v} for k, v in sorted(
        groups.items(), key=lambda kv: -kv[1]["took_max_ms"])]
    return _render(rows, ["shape", "captures", "took_p50_ms",
                          "took_max_ms", "queue_wait_mean_ms"])


def ingest_groups(records: List[dict]) -> Dict[str, dict]:
    """Group tail captures by the write-path events that overlapped
    their window (ISSUE 13): each capture's `ingest_events` annotation
    (attached by the flight recorder from the engine event log) names
    the refresh/merge/flush events in flight while the request ran. The
    split answers "did a merge cause this p99" — a `merge` group with a
    far higher took_p50 than `quiet` is the smoking gun, and
    `events_per_capture` says how churny the overlap was."""
    groups: Dict[str, dict] = {}
    annotated = False
    for rec in records:
        evs = rec.get("ingest_events")
        if evs is None:
            continue            # pre-ISSUE-13 capture: no annotation
        annotated = True
        kinds = sorted({e.get("kind", "?") for e in evs})
        key = "+".join(kinds) if kinds else "quiet"
        g = groups.setdefault(key, {"captures": 0, "events": 0,
                                    "took_ms": []})
        g["captures"] += 1
        g["events"] += len(evs)
        g["took_ms"].append(float(rec.get("took_ms") or 0.0))
    if not annotated:
        return {}
    out: Dict[str, dict] = {}
    for key, g in groups.items():
        took = sorted(g["took_ms"])
        out[key] = {
            "captures": g["captures"],
            "events_per_capture": round(g["events"]
                                        / max(g["captures"], 1), 2),
            "took_p50_ms": round(took[len(took) // 2], 3),
            "took_max_ms": round(took[-1], 3),
        }
    return out


def render_ingest(groups: Dict[str, dict]) -> str:
    rows = [{"ingest_overlap": k, **v} for k, v in sorted(groups.items())]
    return _render(rows, ["ingest_overlap", "captures",
                          "events_per_capture", "took_p50_ms",
                          "took_max_ms"])


def device_groups(records: List[dict]) -> Dict[str, dict]:
    """Group SPMD collective-phase events by device (ISSUE 14): a
    capture whose timeline carries `partial` events was served by the
    shard_map program with the SPMD timeline on — per device, the
    partial-wall distribution and how often the `merge` event named it
    the straggler. The split answers the sharded-serving tail question
    the way coalesce_groups answers the scheduler's: is the p99 one
    lame chip (one device owns the straggler column) or uniform load
    (straggler hits spread evenly)?"""
    groups: Dict[str, dict] = {}
    skews: List[float] = []
    for rec in records:
        for ev in rec.get("events") or []:
            if ev.get("event") == "partial":
                dev = str(ev.get("device", "?"))
                g = groups.setdefault(dev, {
                    "partials": 0, "wall_ms": [], "straggler_hits": 0})
                g["partials"] += 1
                g["wall_ms"].append(float(ev.get("ms", 0.0) or 0.0))
            elif ev.get("event") == "merge":
                skews.append(float(ev.get("skew_ms", 0.0) or 0.0))
                straggler = ev.get("straggler")
                if straggler is not None:
                    g = groups.setdefault(str(straggler), {
                        "partials": 0, "wall_ms": [],
                        "straggler_hits": 0})
                    g["straggler_hits"] += 1
    out: Dict[str, dict] = {}
    for dev, g in groups.items():
        walls = sorted(g["wall_ms"]) or [0.0]
        out[dev] = {
            "partials": g["partials"],
            "wall_p50_ms": round(walls[len(walls) // 2], 3),
            "wall_max_ms": round(walls[-1], 3),
            "straggler_hits": g["straggler_hits"],
        }
    if out and skews:
        skews.sort()
        out["_skew"] = {"partials": len(skews),
                        "wall_p50_ms": round(skews[len(skews) // 2], 3),
                        "wall_max_ms": round(skews[-1], 3),
                        "straggler_hits": "-"}
    return out


def render_devices(groups: Dict[str, dict]) -> str:
    rows = [{"device": k, **v} for k, v in sorted(
        groups.items(), key=lambda kv: (kv[0] == "_skew", kv[0]))]
    return _render(rows, ["device", "partials", "wall_p50_ms",
                          "wall_max_ms", "straggler_hits"])


def rejection_groups(records: List[dict]) -> Dict[str, dict]:
    """Group captures that carry a `reject` lifecycle event by the
    structured reason + tenant the admission controller stamped
    (`deadline_shed` | `tenant_quota` | `breaker:<name>` |
    `backpressure`, ISSUE 11). `items` sums per-item msearch rejects
    (the event's `items` field, 1 for the single-search path);
    `reject_ms` tracks how fast the node turned the rejections around —
    the <5 ms shed-latency contract, eyeballable per group."""
    groups: Dict[str, dict] = {}
    for rec in records:
        for ev in rec.get("events") or []:
            if ev.get("event") != "reject":
                continue
            key = f"{ev.get('reason', '?')}" \
                  f"[{ev.get('tenant', '_default')}]"
            g = groups.setdefault(
                key, {"captures": 0, "items": 0, "max_took_ms": 0.0})
            g["captures"] += 1
            g["items"] += int(ev.get("items", 1))
            g["max_took_ms"] = max(g["max_took_ms"],
                                   float(rec.get("took_ms") or 0.0))
    return groups


def render_rejections(groups: Dict[str, dict]) -> str:
    rows = [{"reason": k, **{kk: f"{vv:g}" if kk == "max_took_ms"
                             else vv for kk, vv in v.items()}}
            for k, v in sorted(groups.items())]
    return _render(rows, ["reason", "captures", "items", "max_took_ms"])


def main(argv: List[str]) -> int:
    min_attr = None
    args: List[str] = []
    rest = list(argv[1:])
    while rest:
        a = rest.pop(0)
        if a.startswith("--assert-attribution"):
            min_attr = float(a.split("=", 1)[1]) if "=" in a \
                else float(rest.pop(0))
        else:
            args.append(a)
    path = args[0] if args else "-"
    records = load_records(path)
    if not records:
        print("no tail captures found (enable the flight recorder: "
              "POST /_telemetry/tail/_enable, then re-run traffic)")
        return 1
    rows = report_rows(records)
    print(f"{len(records)} captured slow request(s)   "
          f"(* = device_get nested inside query, not summed)")
    print(render_table(rows))
    co = coalesce_groups(records)
    if co:
        print("\ntail by coalesce state (co_batched > 1 = shared wave):")
        print(render_coalesce(co))
    sg = shape_groups(records)
    if sg:
        print("\ntail by shape class (which shape owns the p99):")
        print(render_shapes(sg))
    ig = ingest_groups(records)
    if ig:
        print("\ntail by ingest overlap (write-path events in flight "
              "during the capture window):")
        print(render_ingest(ig))
    dg = device_groups(records)
    if dg:
        print("\ntail by device (SPMD partial walls + straggler "
              "attribution; _skew = per-query max-median):")
        print(render_devices(dg))
    groups = rejection_groups(records)
    if groups:
        print(f"\nrejections by reason "
              f"({sum(g['items'] for g in groups.values())} item(s) "
              f"across {sum(g['captures'] for g in groups.values())} "
              f"capture(s)):")
        print(render_rejections(groups))
    attrs = [r["attr_pct"] for r in rows]
    print(f"\nattribution: min {min(attrs):.1f}%  "
          f"mean {sum(attrs) / len(attrs):.1f}%")
    if min_attr is not None:
        under = [r for r in rows if r["attr_pct"] < min_attr]
        if under:
            print(f"FAIL: {len(under)} capture(s) under "
                  f"{min_attr:g}% attribution")
            return 1
        print(f"OK: every capture >= {min_attr:g}% attributed")
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv))
