"""Config-1 (BM25 match) scaling curve: 100K / 300K / 1M docs.

Writes one JSON line per scale to SCALING_raw.json: batched QPS, single-
query p50/p99, the numpy-CSR baseline, and the per-query bytes the
candidate kernel actually touches (posting blocks of the query's terms)
vs what a dense scan would touch. Run on whatever backend is up; the
driver's TPU bench covers the flagship number."""
import json
import os
import sys
import time

import jax
if os.environ.get("SCALE_FORCE_CPU") == "1":
    jax.config.update("jax_platforms", "cpu")
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402


def run_scale(n_docs: int, out):
    from opensearch_tpu.search.executor import SearchExecutor, ShardReader
    from opensearch_tpu.utils.demo import build_shards, query_terms
    t0 = time.perf_counter()
    mapper, segments = build_shards(n_docs, n_shards=1, vocab_size=20000,
                                    avg_len=60, seed=42)
    seg = segments[0]
    build_s = time.perf_counter() - t0
    reader = ShardReader(mapper, segments)
    ex = SearchExecutor(reader)
    queries = query_terms(1024, 20000, seed=7, terms_per_query=2)
    bodies = [{"query": {"match": {"body": q}}, "size": 10} for q in queries]
    ex.multi_search(bodies)                      # compile all shape buckets
    times = []
    for _ in range(3):
        t0 = time.perf_counter()
        ex.multi_search(bodies)
        times.append(time.perf_counter() - t0)
    qps = len(bodies) / sorted(times)[1]
    for q in queries[:32]:
        ex.search({"query": {"match": {"body": q}}, "size": 10})
    lat = []
    for q in queries[:64]:
        t0 = time.perf_counter()
        ex.search({"query": {"match": {"body": q}}, "size": 10})
        lat.append((time.perf_counter() - t0) * 1000)
    lat.sort()
    # bytes the candidate kernel touches per query: the terms' posting
    # blocks (docs int32 + tf f32 = 8B per lane incl. padding lanes)
    per_q_bytes = []
    for q in queries:
        b = 0
        for t in q.split():
            tm = seg.get_term("body", t)
            if tm is not None:
                b += tm.num_blocks * 128 * 8
        per_q_bytes.append(b)
    dense_bytes = seg.post_docs.shape[0] * 128 * 8
    # numpy-CSR baseline (same scorer as bench.py)
    sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    import bench
    base_qps = bench.numpy_baseline(seg, queries[:256])
    rec = {
        "n_docs": n_docs,
        "platform": jax.devices()[0].platform,
        "build_s": round(build_s, 1),
        "qps_batched": round(qps, 1),
        "p50_ms": round(lat[len(lat) // 2], 2),
        "p99_ms": round(lat[min(len(lat) - 1, int(len(lat) * 0.99))], 2),
        "numpy_baseline_qps": round(base_qps, 1),
        "vs_baseline": round(qps / base_qps, 3),
        "scanned_bytes_per_query_p50": int(np.median(per_q_bytes)),
        "scanned_bytes_per_query_max": int(max(per_q_bytes)),
        "dense_scan_bytes": int(dense_bytes),
        "total_postings_blocks": int(seg.post_docs.shape[0]),
    }
    out.write(json.dumps(rec) + "\n")
    out.flush()
    print(json.dumps(rec))


if __name__ == "__main__":
    scales = [int(s) for s in
              os.environ.get("SCALES", "100000,300000,1000000").split(",")]
    path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "SCALING_raw.json")
    with open(path, "a") as out:
        for n in scales:
            run_scale(n, out)
