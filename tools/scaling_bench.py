"""Config-1 (BM25 match) scaling curve: 100K / 300K / 1M docs.

Writes one JSON line per scale to SCALING_raw.json: batched QPS, single-
query p50/p99, the numpy-CSR baseline, and the per-query bytes the
candidate kernel actually touches (posting blocks of the query's terms)
vs what a dense scan would touch. Run on whatever backend is up; the
driver's TPU bench covers the flagship number.

SCALE_FAST=1 (ISSUE 20) swaps the per-doc builder for the vectorized
`build_shards_fast` corpus (burst-clustered mid-band terms, queries
drawn from the materialized band) so the curve extends to 10M docs —
`build_shards` takes hours there; the fast seal takes seconds.
SCALE_BLOCKMAX=1 additionally runs the pruned arm: flips the
`search.blockmax.enabled` module gate and records the live scan
counters' effective (post-pruning) bytes + pruned fraction next to the
static column. The numpy baseline is skipped for fast corpora (the
CSR scorer rebuilds per-doc structures the fast seal never makes)."""
import json
import os
import sys
import time

import jax
if os.environ.get("SCALE_FORCE_CPU") == "1":
    jax.config.update("jax_platforms", "cpu")
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402


def run_scale(n_docs: int, out):
    from opensearch_tpu.search.executor import SearchExecutor, ShardReader
    from opensearch_tpu.utils.demo import (build_shards, build_shards_fast,
                                           fast_query_terms, query_terms)
    fast = os.environ.get("SCALE_FAST") == "1"
    blockmax = os.environ.get("SCALE_BLOCKMAX") == "1"
    t0 = time.perf_counter()
    if fast:
        mapper, segments, fterms = build_shards_fast(
            n_docs, n_shards=1, vocab_size=20000, avg_len=60, seed=42,
            materialize_terms=64, burst_tf=30, burst_window=256,
            doc_len_cv=0.5)
    else:
        mapper, segments = build_shards(n_docs, n_shards=1,
                                        vocab_size=20000,
                                        avg_len=60, seed=42)
    seg = segments[0]
    build_s = time.perf_counter() - t0
    if blockmax:
        from opensearch_tpu.ops import bm25 as _bm25
        from opensearch_tpu.telemetry import TELEMETRY
        _bm25.BLOCKMAX = True
        TELEMETRY.scan.reset()  # per-scale counters (multi-scale runs)
    reader = ShardReader(mapper, segments)
    ex = SearchExecutor(reader)
    queries = fast_query_terms(1024, fterms, seed=7) if fast \
        else query_terms(1024, 20000, seed=7, terms_per_query=2)
    bodies = [{"query": {"match": {"body": q}}, "size": 10} for q in queries]
    ex.multi_search(bodies)                      # compile all shape buckets
    times = []
    for _ in range(3):
        t0 = time.perf_counter()
        ex.multi_search(bodies)
        times.append(time.perf_counter() - t0)
    qps = len(bodies) / sorted(times)[1]
    for q in queries[:32]:
        ex.search({"query": {"match": {"body": q}}, "size": 10})
    lat = []
    for q in queries[:64]:
        t0 = time.perf_counter()
        ex.search({"query": {"match": {"body": q}}, "size": 10})
        lat.append((time.perf_counter() - t0) * 1000)
    lat.sort()
    # bytes the candidate kernel touches per query: the terms' posting
    # blocks (docs int32 + tf f32 = 8B per lane incl. padding lanes)
    per_q_bytes = []
    for q in queries:
        b = 0
        for t in q.split():
            tm = seg.get_term("body", t)
            if tm is not None:
                b += tm.num_blocks * 128 * 8
        per_q_bytes.append(b)
    dense_bytes = seg.post_docs.shape[0] * 128 * 8
    rec = {
        "n_docs": n_docs,
        "platform": jax.devices()[0].platform,
        "build_s": round(build_s, 1),
        "qps_batched": round(qps, 1),
        "p50_ms": round(lat[len(lat) // 2], 2),
        "p99_ms": round(lat[min(len(lat) - 1, int(len(lat) * 0.99))], 2),
        "scanned_bytes_per_query_p50": int(np.median(per_q_bytes)),
        "scanned_bytes_per_query_max": int(max(per_q_bytes)),
        "dense_scan_bytes": int(dense_bytes),
        "total_postings_blocks": int(seg.post_docs.shape[0]),
    }
    if fast:
        rec["fast_corpus"] = True
    else:
        # numpy-CSR baseline (same scorer as bench.py); classic corpora
        # only — the scorer rebuilds per-doc CSR structures the fast
        # seal never materializes
        sys.path.insert(0, os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))))
        import bench
        base_qps = bench.numpy_baseline(seg, queries[:256])
        rec["numpy_baseline_qps"] = round(base_qps, 1)
        rec["vs_baseline"] = round(qps / base_qps, 3)
    if blockmax:
        from opensearch_tpu.telemetry import TELEMETRY
        scan = TELEMETRY.scan.stats()
        post_total = scan["posting_bytes_total"]
        rec["blockmax"] = True
        rec["effective_bytes_per_query_p50"] = \
            scan["per_query"]["effective_posting_bytes"].get("p50")
        rec["pruned_fraction"] = round(
            scan["pruned_bytes_total"] / max(post_total, 1), 4)
    out.write(json.dumps(rec) + "\n")
    out.flush()
    print(json.dumps(rec))


if __name__ == "__main__":
    scales = [int(s) for s in
              os.environ.get("SCALES", "100000,300000,1000000").split(",")]
    path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "SCALING_raw.json")
    with open(path, "a") as out:
        for n in scales:
            run_scale(n, out)
