#!/usr/bin/env python
"""Render the segment-churn ledger as a per-event table + verdict mix.

Input: any JSON/JSONL artifact that carries churn records — a saved
`GET /_telemetry/ingest` response ({"churn": {"records": [...]}}), a
bare list of churn records, or bench.py interference output lines
(records embedding a "churn_records" list). The table is the ISSUE 16
acceptance surface in one place: per refresh/merge, how many bytes the
event actually shipped (delta publish), how many interned memo entries
it invalidated vs kept (segment-keyed carry), and where each event's
recompile verdict LANDED (warm hit / precompiled off-path / paid on a
serving thread).

    python tools/churn_report.py ingest_dump.json
    python tools/churn_report.py BENCH_INTERFERENCE_r02.json
"""

from __future__ import annotations

import json
import sys
from typing import Dict, List

COLUMNS = ("churn_id", "kind", "docs", "upload_bytes",
           "live_mask_bytes", "memo_invalidations", "memo_entries_kept",
           "verdict", "precompile_ms")


def extract_records(obj) -> List[dict]:
    """Pull churn records out of any of the accepted shapes."""
    if isinstance(obj, list):
        out: List[dict] = []
        for item in obj:
            out.extend(extract_records(item))
        return out
    if not isinstance(obj, dict):
        return []
    if "verdict" in obj and ("upload_bytes" in obj or "kind" in obj):
        return [obj]
    out = []
    for key in ("churn_records", "records"):
        if isinstance(obj.get(key), list):
            out.extend(extract_records(obj[key]))
    if isinstance(obj.get("churn"), dict):
        out.extend(extract_records(obj["churn"]))
    return out


def load(path: str) -> List[dict]:
    """JSON file or JSONL file → churn records."""
    text = open(path).read().strip()
    if not text:
        return []
    try:
        return extract_records(json.loads(text))
    except json.JSONDecodeError:
        pass
    records: List[dict] = []
    for line in text.splitlines():
        line = line.strip()
        if not line:
            continue
        try:
            records.extend(extract_records(json.loads(line)))
        except json.JSONDecodeError:
            continue
    return records


def verdict_mix(records: List[dict]) -> Dict[str, int]:
    mix: Dict[str, int] = {}
    for rec in records:
        v = str(rec.get("verdict", "none"))
        mix[v] = mix.get(v, 0) + 1
    return mix


def render(records: List[dict]) -> str:
    """The per-event table + totals footer."""
    table = [list(COLUMNS)]
    for rec in records:
        table.append([str(rec.get(c, "-")) for c in COLUMNS])
    widths = [max(len(row[i]) for row in table)
              for i in range(len(COLUMNS))]
    lines = ["  ".join(cell.ljust(w) for cell, w in zip(row, widths))
             .rstrip() for row in table]
    upload = sum(int(r.get("upload_bytes", 0) or 0) for r in records)
    inval = sum(int(r.get("memo_invalidations",
                          r.get("memo_entries_dropped", 0)) or 0)
                for r in records)
    kept = sum(int(r.get("memo_entries_kept", 0) or 0) for r in records)
    mix = verdict_mix(records)
    lines.append("")
    lines.append(f"events: {len(records)}  upload_bytes: {upload}  "
                 f"memo_invalidations: {inval}  memo_entries_kept: "
                 f"{kept}")
    lines.append("verdict mix: " + ", ".join(
        f"{k}={v}" for k, v in sorted(mix.items())))
    return "\n".join(lines)


def main(argv: List[str]) -> int:
    if len(argv) != 2:
        print("usage: churn_report.py INGEST_DUMP.json")
        return 2
    records = load(argv[1])
    if not records:
        print(f"no churn records in {argv[1]}")
        return 2
    print(render(records))
    # the acceptance tripwire reads straight off the footer: any event
    # whose compile landed on a serving thread is called out loudly
    on_serve = verdict_mix(records).get("recompile-on-serve", 0)
    if on_serve:
        print(f"\nWARNING: {on_serve} event(s) paid an XLA compile on "
              f"a serving thread (recompile-on-serve)")
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv))
