#!/usr/bin/env python
"""Diff two bench dumps; fail on warm-latency regression.

Input: two files of bench.py output records (BENCH_*.json /
BENCH_CONC_*.json / BENCH_ALL.json style — one JSON object per line,
each carrying "metric" or "mode" plus latency fields). Configs are
matched by "mode" when present, else by the "metric" name with the
trailing platform/shape suffix kept (the same config always renders the
same metric string).

The gate: any config whose warm p50 ("warm_p50_ms", falling back to
"p50_ms" for configs without a warmup pass) OR warm p99 regresses by
more than --threshold (default 10%) fails the run with exit code 1 —
the CI tripwire for "this PR made warm serving slower". The p99 side is
what the open-loop concurrent-clients records (bench.py --clients →
BENCH_CONC_*.json) exist for: a scheduler change can hold p50 while
destroying the tail, and a p50-only gate would wave it through. Warm
p99 comes from "warm_p99_ms"; open-loop records (identified by their
"clients" field) are warm by construction, so their "p99_ms" counts.
Configs present in only one file are reported but never fail (bench
sets grow PR over PR); configs without a p99 field skip the p99 gate.

    python tools/bench_compare.py BENCH_r05.json BENCH_r06.json
    python tools/bench_compare.py --threshold 15 old.json new.json
    python tools/bench_compare.py BENCH_CONC_r01.json BENCH_CONC_r02.json
"""

from __future__ import annotations

import json
import sys
from typing import Dict, List, Optional, Tuple

WARM_KEYS = ("warm_p50_ms", "p50_ms")


def load_records(path: str) -> Dict[str, dict]:
    """file of JSON lines (or one JSON array) → {config key: record}."""
    text = open(path).read().strip()
    if not text:
        return {}
    records: List[dict] = []
    if text[0] == "[":
        records = [r for r in json.loads(text) if isinstance(r, dict)]
    else:
        for line in text.splitlines():
            line = line.strip()
            if not line:
                continue
            try:
                obj = json.loads(line)
            except json.JSONDecodeError:
                continue
            if isinstance(obj, dict):
                records.append(obj)
    out: Dict[str, dict] = {}
    for rec in records:
        key = rec.get("mode") or rec.get("metric")
        if key and "error" not in rec:
            out[str(key)] = rec      # latest record per config wins
    return out


def warm_p50(rec: dict) -> Optional[float]:
    for key in WARM_KEYS:
        v = rec.get(key)
        if isinstance(v, (int, float)) and v > 0:
            return float(v)
    return None


def warm_p99(rec: dict) -> Optional[float]:
    """Warm tail latency: explicit "warm_p99_ms", or bare "p99_ms" for
    open-loop concurrent-mode records (their measured window is warm by
    construction — bench.py warms before the arrival schedule starts).
    Cold-inclusive p99_ms on other configs deliberately does NOT count:
    its compile cliff is box-state noise, not a serving regression."""
    v = rec.get("warm_p99_ms")
    if isinstance(v, (int, float)) and v > 0:
        return float(v)
    if "clients" in rec or "arrival_rate" in rec:
        v = rec.get("p99_ms")
        if isinstance(v, (int, float)) and v > 0:
            return float(v)
    return None


def compare(old: Dict[str, dict], new: Dict[str, dict],
            threshold_pct: float) -> Tuple[List[dict], List[str]]:
    """→ (rows, failures). A row per config in either file."""
    rows, failures = [], []
    for key in sorted(set(old) | set(new)):
        o, n = old.get(key), new.get(key)
        row = {"config": key}
        if o is None or n is None:
            row["status"] = "old-only" if n is None else "new-only"
            rows.append(row)
            continue
        ov, nv = warm_p50(o), warm_p50(n)
        row["old_warm_p50_ms"] = ov
        row["new_warm_p50_ms"] = nv
        if ov is None or nv is None:
            row["status"] = "no-latency-field"
            rows.append(row)
            continue
        delta_pct = 100.0 * (nv - ov) / ov
        row["delta_pct"] = round(delta_pct, 1)
        status = "ok"
        if delta_pct > threshold_pct:
            status = "REGRESSION"
            failures.append(
                f"{key}: warm p50 {ov}ms -> {nv}ms "
                f"(+{delta_pct:.1f}% > {threshold_pct:g}%)")
        # the tail gate: both sides must carry a warm p99 (configs
        # without one skip — the p50 verdict stands alone)
        o99, n99 = warm_p99(o), warm_p99(n)
        if o99 is not None and n99 is not None:
            row["old_warm_p99_ms"] = o99
            row["new_warm_p99_ms"] = n99
            d99 = 100.0 * (n99 - o99) / o99
            row["p99_delta_pct"] = round(d99, 1)
            if d99 > threshold_pct:
                status = "REGRESSION"
                failures.append(
                    f"{key}: warm p99 {o99}ms -> {n99}ms "
                    f"(+{d99:.1f}% > {threshold_pct:g}%)")
        row["status"] = status
        rows.append(row)
    return rows, failures


def render(rows: List[dict]) -> str:
    headers = ["config", "old_warm_p50_ms", "new_warm_p50_ms",
               "delta_pct", "old_warm_p99_ms", "new_warm_p99_ms",
               "p99_delta_pct", "status"]
    table = [headers] + [[str(r.get(h, "-")) for h in headers]
                         for r in rows]
    widths = [max(len(row[i]) for row in table)
              for i in range(len(headers))]
    return "\n".join(
        "  ".join(cell.ljust(w) for cell, w in zip(row, widths)).rstrip()
        for row in table)


def main(argv: List[str]) -> int:
    threshold = 10.0
    args: List[str] = []
    rest = list(argv[1:])
    while rest:
        a = rest.pop(0)
        if a.startswith("--threshold"):
            threshold = float(a.split("=", 1)[1]) if "=" in a \
                else float(rest.pop(0))
        else:
            args.append(a)
    if len(args) != 2:
        print("usage: bench_compare.py [--threshold PCT] OLD.json NEW.json")
        return 2
    old, new = load_records(args[0]), load_records(args[1])
    if not old or not new:
        print(f"no parsable bench records in "
              f"{args[0] if not old else args[1]}")
        return 2
    rows, failures = compare(old, new, threshold)
    print(render(rows))
    if failures:
        print(f"\nFAIL: {len(failures)} regression(s) "
              f"beyond {threshold:g}% on warm p50/p99:")
        for f in failures:
            print(f"  {f}")
        return 1
    print(f"\nOK: no warm-p50/p99 regression beyond {threshold:g}%")
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv))
