#!/usr/bin/env python
"""Diff two bench dumps; fail on warm-latency regression.

Input: two files of bench.py output records (BENCH_*.json /
BENCH_CONC_*.json / BENCH_ALL.json style — one JSON object per line,
each carrying "metric" or "mode" plus latency fields). Configs are
matched by "mode" when present, else by the "metric" name with the
trailing platform/shape suffix kept (the same config always renders the
same metric string).

The gate: any config whose warm p50 ("warm_p50_ms", falling back to
"p50_ms" for configs without a warmup pass) OR warm p99 regresses by
more than --threshold (default 10%) fails the run with exit code 1 —
the CI tripwire for "this PR made warm serving slower". The p99 side is
what the open-loop concurrent-clients records (bench.py --clients →
BENCH_CONC_*.json) exist for: a scheduler change can hold p50 while
destroying the tail, and a p50-only gate would wave it through. Warm
p99 comes from "warm_p99_ms"; open-loop records (identified by their
"clients" field) are warm by construction, so their "p99_ms" counts.
Configs present in only one file are reported but never fail (bench
sets grow PR over PR); configs without a p99 field skip the p99 gate.

    python tools/bench_compare.py BENCH_r05.json BENCH_r06.json
    python tools/bench_compare.py --threshold 15 old.json new.json
    python tools/bench_compare.py BENCH_CONC_r01.json BENCH_CONC_r02.json
"""

from __future__ import annotations

import json
import sys
from typing import Dict, List, Optional, Tuple

WARM_KEYS = ("warm_p50_ms", "p50_ms")

# the overload-sweep gate (ISSUE 11): goodput past the knee may not
# collapse by more than this between two curves — the "degrades
# gracefully" contract, distinct from the warm-latency threshold
OVERLOAD_COLLAPSE_PCT = 15.0

# the interference gate (ISSUE 13): at the SAME ingest rate, search p99
# may not degrade by more than this between two rounds, and ingest
# throughput may not drop by more than this — "serving under writes got
# slower" and "writes under serving got slower" both fail the run
INTERFERENCE_P99_PCT = 15.0

# the multi-chip scaling gate (ISSUE 14): at EQUAL device count D,
# per-chip scaling efficiency QPS(D)/(D·QPS(1)) may not drop by more
# than this between two SCALING_MC rounds — "adding chips stopped
# paying" fails the run even when absolute QPS moved with box state
SCALING_EFFICIENCY_PCT = 15.0

# the insights gate (ISSUE 15): at EQUAL shape key, a shape class's
# warm p99 may not degrade by more than this between two INSIGHTS
# rounds — "this query class got slower" fails the run even when the
# overall mix shifted. Shapes need a minimal sample count on both
# sides: a 3-request shape's p99 is one unlucky request, not a class.
INSIGHTS_P99_PCT = 15.0
INSIGHTS_MIN_COUNT = 20

# the late-interaction gate (ISSUE 18): at EQUAL config key, MaxSim
# recall@10 may not drop by more than this (absolute) between rounds,
# and the PQ arm's recall-vs-exact must clear the committed floor on
# the new side unconditionally (BENCH_MAXSIM_r01.json acceptance)
MAXSIM_RECALL_DROP = 0.02
MAXSIM_PQ_RECALL_FLOOR = 0.95

# the block-max gate (ISSUE 20): within the NEW round, the pruned arm
# of a blockmax A/B (mode `X_bmx` next to its unpruned `X`) must carry
# a top-k page digest IDENTICAL to the unpruned arm's — rank-exactness
# is the pruning kernel's contract, checked in CI, never assumed — and
# at ≤1M docs its warm p50 may not exceed the unpruned arm's by more
# than this: below the trigger scale pruning pays little back, so the
# A/B pins the price of serving with the gate on
BLOCKMAX_P50_PCT = 15.0
BLOCKMAX_P50_MAX_DOCS = 1_000_000

# the kernel-profiler gate (ISSUE 19): at EQUAL bench+family key, a
# kernel family's sampled device-wall p50 may not regress by more than
# this between two BENCH_KERNELS rounds — "this executable family got
# slower on device" fails the run even when the end-to-end warm
# latency absorbed it elsewhere
KERNELS_P50_PCT = 15.0


def load_records(path: str) -> Dict[str, dict]:
    """file of JSON lines (or one JSON array) → {config key: record}."""
    text = open(path).read().strip()
    if not text:
        return {}
    records: List[dict] = []
    if text[0] == "[":
        records = [r for r in json.loads(text) if isinstance(r, dict)]
    else:
        for line in text.splitlines():
            line = line.strip()
            if not line:
                continue
            try:
                obj = json.loads(line)
            except json.JSONDecodeError:
                continue
            if isinstance(obj, dict):
                records.append(obj)
    out: Dict[str, dict] = {}
    for rec in records:
        key = rec.get("mode") or rec.get("metric")
        if key and "error" not in rec:
            out[str(key)] = rec      # latest record per config wins
    return out


def warm_p50(rec: dict) -> Optional[float]:
    for key in WARM_KEYS:
        v = rec.get(key)
        if isinstance(v, (int, float)) and v > 0:
            return float(v)
    return None


def warm_p99(rec: dict) -> Optional[float]:
    """Warm tail latency: explicit "warm_p99_ms", or bare "p99_ms" for
    open-loop concurrent-mode records (their measured window is warm by
    construction — bench.py warms before the arrival schedule starts).
    Cold-inclusive p99_ms on other configs deliberately does NOT count:
    its compile cliff is box-state noise, not a serving regression."""
    v = rec.get("warm_p99_ms")
    if isinstance(v, (int, float)) and v > 0:
        return float(v)
    if "clients" in rec or "arrival_rate" in rec:
        v = rec.get("p99_ms")
        if isinstance(v, (int, float)) and v > 0:
            return float(v)
    return None


def compare(old: Dict[str, dict], new: Dict[str, dict],
            threshold_pct: float) -> Tuple[List[dict], List[str]]:
    """→ (rows, failures). A row per config in either file."""
    rows, failures = [], []
    for key in sorted(set(old) | set(new)):
        o, n = old.get(key), new.get(key)
        if any(r is not None and "offered_rate" in r
               and "goodput_qps" in r for r in (o, n)):
            # BENCH_OVERLOAD ramp points have their own gate
            # (compare_overload): their bare p50/p99 are open-loop
            # intended-arrival latencies that grow without bound past
            # saturation BY CONSTRUCTION and scale with each round's
            # independently measured saturation reference — gating
            # them as warm latency would fail identical builds
            continue
        if any(r is not None and "ingest_rate" in r for r in (o, n)):
            # BENCH_INTERFERENCE points have their own gate
            # (compare_interference, 15% at equal ingest rate): their
            # p99 under concurrent ingest includes churn-induced
            # compile stalls the generic warm gate would misread
            continue
        if any(r is not None and "devices" in r
               and "per_chip_efficiency" in r for r in (o, n)):
            # SCALING_MC points have their own gate (compare_scaling):
            # per-chip EFFICIENCY is round-normalized (divided by the
            # same round's QPS(1)), where absolute warm latency on the
            # virtual-chip CPU box moves with box state
            continue
        if any(r is not None and isinstance(r.get("insights"), dict)
               and "shapes" in r["insights"] for r in (o, n)):
            # INSIGHTS records have their own gate (compare_insights,
            # per-shape warm p99 at equal shape key): their aggregate
            # p99 moves with the shape MIX, which shifts legitimately
            # round over round
            continue
        if any(r is not None and isinstance(r.get("family"), str)
               and "device_ms" in r for r in (o, n)):
            # BENCH_KERNELS rows have their own gate (compare_kernels,
            # per-family device p50 at equal bench+family key): their
            # p50_ms is a sampled device EXEC wall, not a warm request
            # latency — the generic warm gate would misread it
            continue
        row = {"config": key}
        if o is None or n is None:
            row["status"] = "old-only" if n is None else "new-only"
            rows.append(row)
            continue
        ov, nv = warm_p50(o), warm_p50(n)
        row["old_warm_p50_ms"] = ov
        row["new_warm_p50_ms"] = nv
        if ov is None or nv is None:
            row["status"] = "no-latency-field"
            rows.append(row)
            continue
        delta_pct = 100.0 * (nv - ov) / ov
        row["delta_pct"] = round(delta_pct, 1)
        status = "ok"
        if delta_pct > threshold_pct:
            status = "REGRESSION"
            failures.append(
                f"{key}: warm p50 {ov}ms -> {nv}ms "
                f"(+{delta_pct:.1f}% > {threshold_pct:g}%)")
        # the tail gate: both sides must carry a warm p99 (configs
        # without one skip — the p50 verdict stands alone)
        o99, n99 = warm_p99(o), warm_p99(n)
        if o99 is not None and n99 is not None:
            row["old_warm_p99_ms"] = o99
            row["new_warm_p99_ms"] = n99
            d99 = 100.0 * (n99 - o99) / o99
            row["p99_delta_pct"] = round(d99, 1)
            if d99 > threshold_pct:
                status = "REGRESSION"
                failures.append(
                    f"{key}: warm p99 {o99}ms -> {n99}ms "
                    f"(+{d99:.1f}% > {threshold_pct:g}%)")
        # open-loop concurrency records (BENCH_CONC shape, ISSUE 12):
        # gate THROUGHPUT too — a scheduler change must not trade
        # open-loop QPS away under the same offered load (the p99 gate
        # above already covers the admitted tail: conc records' p99 is
        # warm by construction) — and when the new record ran with the
        # wave scheduler enabled, demand OBSERVED cross-request
        # coalescing: a captured timeline with co_batched > 1, not a
        # config flag
        if "clients" in o or "clients" in n:
            oq, nq = o.get("value"), n.get("value")
            if isinstance(oq, (int, float)) and \
                    isinstance(nq, (int, float)) and oq > 0:
                dq = 100.0 * (nq - oq) / oq
                row["qps_delta_pct"] = round(dq, 1)
                if dq < -threshold_pct:
                    status = "REGRESSION"
                    failures.append(
                        f"{key}: open-loop QPS {oq} -> {nq} "
                        f"({dq:.1f}% < -{threshold_pct:g}%)")
        n_sched = n.get("scheduler")
        if isinstance(n_sched, dict) and n_sched.get("enabled"):
            cb = max(int(n_sched.get("tail_co_batched_max", 0) or 0),
                     int((n_sched.get("co_batched") or {})
                         .get("max", 0) or 0))
            row["co_batched_max"] = cb
            if cb <= 1:
                status = "NO-COALESCE"
                failures.append(
                    f"{key}: scheduler enabled but no captured "
                    f"timeline shows co_batched > 1 (max {cb})")
        row["status"] = status
        rows.append(row)
    return rows, failures


def _overload_records(recs: Dict[str, dict]) -> Dict[str, dict]:
    """The BENCH_OVERLOAD shape: offered-load ramp points carrying
    `offered_rate` + `goodput_qps` (bench.py --overload-sweep)."""
    return {k: r for k, r in recs.items()
            if isinstance(r.get("offered_rate"), (int, float))
            and isinstance(r.get("goodput_qps"), (int, float))}


def _knee_rate(recs: Dict[str, dict]) -> float:
    """The curve's knee: the offered rate of the max-goodput point —
    past it, added offered load buys nothing and the only question is
    whether goodput HOLDS (plateau) or collapses."""
    best = max(recs.values(), key=lambda r: r["goodput_qps"])
    return float(best["offered_rate"])


def compare_overload(old: Dict[str, dict], new: Dict[str, dict],
                     threshold_pct: float) -> Tuple[List[dict], List[str]]:
    """Gate two goodput-vs-offered-load curves: fail on goodput
    collapse (> OVERLOAD_COLLAPSE_PCT drop at-or-past the OLD curve's
    knee) or admitted-p99 breach (new p99 over the record's own SLO
    setting, or over old p99 by more than --threshold). Pre-knee
    goodput moves with box state and never fails; points present in
    only one curve report but never fail (ramps grow round over
    round)."""
    o_recs, n_recs = _overload_records(old), _overload_records(new)
    rows, failures = [], []
    if not o_recs or not n_recs:
        return rows, failures
    knee = _knee_rate(o_recs)
    for key in sorted(set(o_recs) | set(n_recs),
                      key=lambda k: (o_recs.get(k) or n_recs.get(k))
                      ["offered_rate"]):
        o, n = o_recs.get(key), n_recs.get(key)
        row = {"config": key,
               "offered_rate": (o or n)["offered_rate"]}
        if o is None or n is None:
            row["status"] = "old-only" if n is None else "new-only"
            rows.append(row)
            continue
        row["old_goodput"] = o["goodput_qps"]
        row["new_goodput"] = n["goodput_qps"]
        status = "ok"
        delta = 100.0 * (n["goodput_qps"] - o["goodput_qps"]) \
            / max(o["goodput_qps"], 1e-9)
        row["goodput_delta_pct"] = round(delta, 1)
        past_knee = float(o["offered_rate"]) >= knee
        row["past_knee"] = past_knee
        if past_knee and delta < -OVERLOAD_COLLAPSE_PCT:
            status = "COLLAPSE"
            failures.append(
                f"{key}: goodput {o['goodput_qps']} -> "
                f"{n['goodput_qps']} ({delta:+.1f}% past the knee, "
                f"limit -{OVERLOAD_COLLAPSE_PCT:g}%)")
        o99, n99 = o.get("admitted_p99_ms"), n.get("admitted_p99_ms")
        if isinstance(o99, (int, float)) and isinstance(n99, (int, float)):
            row["old_admitted_p99_ms"] = o99
            row["new_admitted_p99_ms"] = n99
            slo = n.get("slo_ms")
            if isinstance(slo, (int, float)) and n99 > slo:
                status = "P99-BREACH"
                failures.append(
                    f"{key}: admitted p99 {n99}ms over the SLO "
                    f"setting [{slo}ms]")
            elif o99 > 0 and 100.0 * (n99 - o99) / o99 > threshold_pct:
                status = "P99-BREACH"
                failures.append(
                    f"{key}: admitted p99 {o99}ms -> {n99}ms "
                    f"(+{100.0 * (n99 - o99) / o99:.1f}% > "
                    f"{threshold_pct:g}%)")
        row["status"] = status
        rows.append(row)
    return rows, failures


def _interference_records(recs: Dict[str, dict]) -> Dict[str, dict]:
    """The BENCH_INTERFERENCE shape: points carrying `ingest_rate` next
    to search latency fields (bench.py --ingest-rate)."""
    return {k: r for k, r in recs.items()
            if isinstance(r.get("ingest_rate"), (int, float))
            and isinstance(r.get("p99_ms"), (int, float))}


def compare_interference(old: Dict[str, dict], new: Dict[str, dict],
                         threshold_pct: float
                         ) -> Tuple[List[dict], List[str]]:
    """Gate two interference sweeps point-by-point at EQUAL ingest
    rate: fail when search p99 degrades more than INTERFERENCE_P99_PCT
    (serving under writes got slower), or when achieved ingest
    throughput (`ingest_dps`) drops more than --threshold (writes under
    serving got slower). Points present in only one round report but
    never fail (rate grids grow round over round); the ingest-off
    control gates like any other point (its ingest_dps is 0 on both
    sides and skips the throughput gate)."""
    o_recs = _interference_records(old)
    n_recs = _interference_records(new)
    rows, failures = [], []
    if not o_recs or not n_recs:
        return rows, failures
    for key in sorted(set(o_recs) | set(n_recs),
                      key=lambda k: (o_recs.get(k) or n_recs.get(k))
                      ["ingest_rate"]):
        o, n = o_recs.get(key), n_recs.get(key)
        row = {"config": key,
               "ingest_rate": (o or n)["ingest_rate"]}
        if o is None or n is None:
            row["status"] = "old-only" if n is None else "new-only"
            rows.append(row)
            continue
        status = "ok"
        o99, n99 = float(o["p99_ms"]), float(n["p99_ms"])
        row["old_p99_ms"] = o99
        row["new_p99_ms"] = n99
        # equal OFFERED rate is the join key, but the rounds only truly
        # compare at equal ACHIEVED pressure — annotate the ratio of
        # achieved docs/s so an "improvement" bought by a slower ingest
        # client is visible in the row (and in any failure message)
        od_, nd_ = o.get("ingest_dps"), n.get("ingest_dps")
        pressure = ""
        if isinstance(od_, (int, float)) and \
                isinstance(nd_, (int, float)) and od_ > 0:
            row["achieved_ratio"] = round(nd_ / od_, 3)
            pressure = (f"; achieved ingest {od_:g} -> {nd_:g} docs/s "
                        f"(x{row['achieved_ratio']:g})")
        if o99 > 0:
            d99 = 100.0 * (n99 - o99) / o99
            row["p99_delta_pct"] = round(d99, 1)
            if d99 > INTERFERENCE_P99_PCT:
                status = "P99-REGRESSION"
                failures.append(
                    f"{key}: search p99 under ingest {o99}ms -> "
                    f"{n99}ms (+{d99:.1f}% > "
                    f"{INTERFERENCE_P99_PCT:g}% at equal ingest rate"
                    f"{pressure})")
        od = o.get("ingest_dps")
        nd = n.get("ingest_dps")
        if isinstance(od, (int, float)) and isinstance(nd, (int, float)) \
                and od > 0:
            row["old_ingest_dps"] = od
            row["new_ingest_dps"] = nd
            dd = 100.0 * (nd - od) / od
            row["ingest_delta_pct"] = round(dd, 1)
            if dd < -threshold_pct:
                status = "INGEST-REGRESSION"
                failures.append(
                    f"{key}: ingest throughput {od} -> {nd} docs/s "
                    f"({dd:.1f}% < -{threshold_pct:g}%)")
        row["status"] = status
        rows.append(row)
    return rows, failures


def _scaling_records(recs: Dict[str, dict]) -> Dict[str, dict]:
    """The SCALING_MC shape: multi-chip points carrying `devices` next
    to a QPS `value` (bench.py --devices)."""
    return {k: r for k, r in recs.items()
            if isinstance(r.get("devices"), (int, float))
            and isinstance(r.get("value"), (int, float))}


def compare_scaling(old: Dict[str, dict], new: Dict[str, dict],
                    threshold_pct: float) -> Tuple[List[dict], List[str]]:
    """Gate two multi-chip scaling curves point-by-point at EQUAL
    device count: fail when per-chip efficiency QPS(D)/(D·QPS(1))
    drops by more than SCALING_EFFICIENCY_PCT (the chips stopped
    pulling their weight), or when straggler skew more than doubles
    past --threshold over a 1 ms floor (a chip went quietly lame).
    Single-chip points (D=1, efficiency 1.0 by construction) gate only
    through the generic warm-latency rows; points present in only one
    round report but never fail (device grids grow round over
    round)."""
    o_recs, n_recs = _scaling_records(old), _scaling_records(new)
    rows, failures = [], []
    if not o_recs or not n_recs:
        return rows, failures
    for key in sorted(set(o_recs) | set(n_recs),
                      key=lambda k: (o_recs.get(k) or n_recs.get(k))
                      ["devices"]):
        o, n = o_recs.get(key), n_recs.get(key)
        row = {"config": key, "devices": (o or n)["devices"]}
        if o is None or n is None:
            row["status"] = "old-only" if n is None else "new-only"
            rows.append(row)
            continue
        status = "ok"
        oe, ne = o.get("per_chip_efficiency"), n.get("per_chip_efficiency")
        if isinstance(oe, (int, float)) and isinstance(ne, (int, float)) \
                and oe > 0:
            row["old_efficiency"] = oe
            row["new_efficiency"] = ne
            de = 100.0 * (ne - oe) / oe
            row["efficiency_delta_pct"] = round(de, 1)
            if de < -SCALING_EFFICIENCY_PCT:
                status = "EFFICIENCY-REGRESSION"
                failures.append(
                    f"{key}: per-chip efficiency {oe} -> {ne} "
                    f"({de:.1f}% < -{SCALING_EFFICIENCY_PCT:g}% at "
                    f"equal D)")
        os_, ns = o.get("straggler_skew_p50_ms"), \
            n.get("straggler_skew_p50_ms")
        if isinstance(os_, (int, float)) and isinstance(ns, (int, float)):
            row["old_skew_p50_ms"] = os_
            row["new_skew_p50_ms"] = ns
            # floor at 1ms: sub-millisecond skews on the virtual-chip
            # box are scheduler noise, not a lame chip
            if ns > max(os_ * 2, 1.0) and \
                    100.0 * (ns - os_) / max(os_, 1e-9) > threshold_pct:
                status = "SKEW-REGRESSION"
                failures.append(
                    f"{key}: straggler skew p50 {os_}ms -> {ns}ms "
                    f"(more than doubled past the 1ms floor)")
        row["status"] = status
        rows.append(row)
    return rows, failures


def _page_records(recs: Dict[str, dict]) -> Dict[str, dict]:
    """The result-page A/B shape: arm records from bench.py --ab-page
    carrying the `result_page` arm marker (BENCH_AB_PAGE*.json)."""
    return {k: r for k, r in recs.items() if "result_page" in r}


def compare_page(old: Dict[str, dict], new: Dict[str, dict],
                 threshold_pct: float) -> Tuple[List[dict], List[str]]:
    """Gate the single-round-trip result page (ISSUE 17): any NEW-side
    arm that ran with the page gate on must have read its whole
    response — merged top-k, sort keys, docvalue lanes, totals, aggs —
    in EXACTLY one device round trip per wave, or the run fails
    (PAGE-MULTI-TRIP). The row also reports the page-bytes vs
    legacy-bytes d2h ratio at equal config key, next to the transfer
    gates: the page pays for its one trip by shipping every merged
    lane as wire bytes, where the legacy tail's extra trips read
    zero-byte host mirrors — the ratio is the measured wire price of
    the single round trip (a few extra KB per wave), reported so a
    future layout change that silently blows the page up is visible,
    not gated (the warm-p50 gate is the arbiter of whether the trade
    still pays). The warm-p50
    side of the A/B rides the generic gate above (the two arms share a
    config key, so the page arm is gated against the legacy arm at
    --threshold like any round-over-round pair). Arms measured without
    --telemetry carry no ledger fields and only report (no-ledger)."""
    del threshold_pct
    o_recs, n_recs = _page_records(old), _page_records(new)
    rows, failures = [], []
    if not n_recs:
        return rows, failures
    for key in sorted(n_recs):
        o, n = o_recs.get(key), n_recs[key]
        row = {"config": key, "result_page": bool(n.get("result_page"))}
        status = "ok"
        rt = n.get("round_trips_per_wave")
        row["round_trips_per_wave"] = rt
        if n.get("result_page"):
            if not isinstance(rt, (int, float)):
                status = "no-ledger"
            elif rt != 1:
                status = "PAGE-MULTI-TRIP"
                failures.append(
                    f"{key}: page arm read {rt} device round trips per "
                    f"wave (the result-page contract is exactly 1)")
        ob = o.get("d2h_bytes_per_wave") if o is not None else None
        nb = n.get("d2h_bytes_per_wave")
        if isinstance(ob, (int, float)) and ob > 0 and \
                isinstance(nb, (int, float)):
            row["old_d2h_bytes_per_wave"] = ob
            row["new_d2h_bytes_per_wave"] = nb
            row["bytes_ratio"] = round(nb / ob, 3)
        row["status"] = status
        rows.append(row)
    return rows, failures


def _insights_records(recs: Dict[str, dict]) -> Dict[str, dict]:
    """The INSIGHTS shape: records carrying an `insights` block with
    per-shape rows (bench.py --insights)."""
    return {k: r for k, r in recs.items()
            if isinstance(r.get("insights"), dict)
            and isinstance(r["insights"].get("shapes"), dict)}


def compare_insights(old: Dict[str, dict], new: Dict[str, dict],
                     threshold_pct: float) -> Tuple[List[dict], List[str]]:
    """Gate two insights records shape-by-shape at EQUAL shape key:
    fail when a shape class's warm p99 regresses by more than
    INSIGHTS_P99_PCT. The shape id is structural (interned-template /
    skeleton hash), so it compares stably across rounds; shapes present
    in only one round report but never fail (workload mixes grow round
    over round), and shapes under INSIGHTS_MIN_COUNT requests on either
    side only report (one slow request is not a class regression).
    `threshold_pct` is accepted for signature parity with the other
    comparers; the per-shape bound is the class constant."""
    del threshold_pct
    o_all, n_all = _insights_records(old), _insights_records(new)
    rows, failures = [], []
    if not o_all or not n_all:
        return rows, failures
    for key in sorted(set(o_all) & set(n_all)):
        o_shapes = o_all[key]["insights"]["shapes"]
        n_shapes = n_all[key]["insights"]["shapes"]
        for shape in sorted(set(o_shapes) | set(n_shapes)):
            o, n = o_shapes.get(shape), n_shapes.get(shape)
            row = {"config": key, "shape": shape}
            if o is None or n is None:
                row["status"] = "old-only" if n is None else "new-only"
                rows.append(row)
                continue
            o99, n99 = o.get("p99_ms"), n.get("p99_ms")
            row["old_count"] = o.get("count", 0)
            row["new_count"] = n.get("count", 0)
            row["old_p99_ms"] = o99
            row["new_p99_ms"] = n99
            status = "ok"
            if not isinstance(o99, (int, float)) or \
                    not isinstance(n99, (int, float)) or o99 <= 0:
                status = "no-latency-field"
            else:
                d99 = 100.0 * (n99 - o99) / o99
                row["p99_delta_pct"] = round(d99, 1)
                small = min(row["old_count"], row["new_count"]) \
                    < INSIGHTS_MIN_COUNT
                if small:
                    status = "low-count"
                elif d99 > INSIGHTS_P99_PCT:
                    status = "SHAPE-REGRESSION"
                    failures.append(
                        f"{key} shape {shape}: warm p99 {o99}ms -> "
                        f"{n99}ms (+{d99:.1f}% > "
                        f"{INSIGHTS_P99_PCT:g}% at equal shape key)")
            row["status"] = status
            rows.append(row)
    return rows, failures


def _maxsim_records(recs: Dict[str, dict]) -> Dict[str, dict]:
    """The MaxSim shape (BENCH_MAXSIM_*.json): records carrying a
    recall_at_10 field with a maxsim mode key."""
    return {k: r for k, r in recs.items()
            if r.get("mode") in ("maxsim", "maxsim_pq")
            and isinstance(r.get("recall_at_10"), (int, float))}


def compare_maxsim(old: Dict[str, dict], new: Dict[str, dict],
                   threshold_pct: float) -> Tuple[List[dict], List[str]]:
    """Gate the late-interaction tier (ISSUE 18) on RECALL, not just
    latency (the warm p50/p99 side rides the generic gate above):

    - at equal config key, recall@10 may not drop by more than
      MAXSIM_RECALL_DROP absolute between rounds — "the kernel got
      faster by returning worse top-k" fails the run;
    - the PQ arm's recall_vs_exact must clear MAXSIM_PQ_RECALL_FLOOR on
      the NEW side unconditionally (the committed acceptance bound) —
      a quantizer regression fails even against an old round that had
      already slipped."""
    del threshold_pct
    o_recs, n_recs = _maxsim_records(old), _maxsim_records(new)
    rows, failures = [], []
    for key in sorted(n_recs):
        n = n_recs[key]
        o = o_recs.get(key)
        row = {"config": key,
               "old_recall_at_10": o.get("recall_at_10")
               if o is not None else None,
               "new_recall_at_10": n["recall_at_10"]}
        status = "ok"
        rve = n.get("recall_vs_exact")
        if isinstance(rve, (int, float)):
            row["recall_vs_exact"] = rve
            if rve < MAXSIM_PQ_RECALL_FLOOR:
                status = "PQ-RECALL-FLOOR"
                failures.append(
                    f"{key}: PQ recall_vs_exact {rve} below the "
                    f"committed floor {MAXSIM_PQ_RECALL_FLOOR}")
        if o is not None and status == "ok":
            drop = float(o["recall_at_10"]) - float(n["recall_at_10"])
            row["recall_drop"] = round(drop, 4)
            if drop > MAXSIM_RECALL_DROP:
                status = "RECALL-REGRESSION"
                failures.append(
                    f"{key}: recall@10 {o['recall_at_10']} -> "
                    f"{n['recall_at_10']} (dropped {drop:.4f} > "
                    f"{MAXSIM_RECALL_DROP:g} at equal config key)")
        elif o is None:
            row["recall_drop"] = None
        row["status"] = status if o is not None or status != "ok" \
            else "new-only"
        rows.append(row)
    return rows, failures


def _kernels_records(recs: Dict[str, dict]) -> Dict[str, dict]:
    """The BENCH_KERNELS shape: per-(bench, family) rows carrying a
    kernel `family` next to a `device_ms` total (bench.py --kernels)."""
    return {k: r for k, r in recs.items()
            if isinstance(r.get("family"), str) and "device_ms" in r}


def compare_kernels(old: Dict[str, dict], new: Dict[str, dict],
                    threshold_pct: float) -> Tuple[List[dict], List[str]]:
    """Gate two kernel-profiler rounds row-by-row at EQUAL bench+family
    key: fail when a family's sampled device-wall p50 regresses by more
    than KERNELS_P50_PCT (that executable family got slower on device).
    Census-only rows (calls == 0 on either side — the family compiled
    but never dispatched in the measured window, so it carries
    compile/roofline data and no timing) report but never fail, as do
    rows present in only one round (the family set grows with the
    feature set). `threshold_pct` is accepted for signature parity with
    the other comparers; the per-family bound is the class constant."""
    del threshold_pct
    o_recs, n_recs = _kernels_records(old), _kernels_records(new)
    rows, failures = [], []
    if not o_recs or not n_recs:
        return rows, failures
    for key in sorted(set(o_recs) | set(n_recs)):
        o, n = o_recs.get(key), n_recs.get(key)
        row = {"config": key, "family": (o or n)["family"]}
        if o is None or n is None:
            row["status"] = "old-only" if n is None else "new-only"
            rows.append(row)
            continue
        status = "ok"
        row["old_calls"] = o.get("calls", 0)
        row["new_calls"] = n.get("calls", 0)
        o50, n50 = o.get("p50_ms"), n.get("p50_ms")
        row["old_p50_ms"] = o50
        row["new_p50_ms"] = n50
        row["bound"] = n.get("bound")
        if not row["old_calls"] or not row["new_calls"]:
            status = "census-only"
        elif isinstance(o50, (int, float)) and o50 > 0 \
                and isinstance(n50, (int, float)):
            d50 = 100.0 * (n50 - o50) / o50
            row["p50_delta_pct"] = round(d50, 1)
            if d50 > KERNELS_P50_PCT:
                status = "KERNEL-REGRESSION"
                failures.append(
                    f"{key}: device p50 {o50}ms -> {n50}ms "
                    f"(+{d50:.1f}% > {KERNELS_P50_PCT:g}% at equal "
                    f"bench+family key)")
        else:
            status = "no-latency-field"
        row["status"] = status
        rows.append(row)
    return rows, failures


def _blockmax_pairs(recs: Dict[str, dict]) -> List[Tuple[str, Optional[dict], dict]]:
    """(base key, unpruned record or None, pruned record) for every
    pruned-arm record (`blockmax: true`, mode suffixed `_bmx`) in the
    set. The unpruned partner is the record at the arm-neutral key —
    matched from the full set, so harnesses that only tag the pruned
    arm (the open-loop records) still pair."""
    pairs = []
    for key, on in sorted(recs.items()):
        if not key.endswith("_bmx") or on.get("blockmax") is not True:
            continue
        pairs.append((key[:-4], recs.get(key[:-4]), on))
    return pairs


def compare_blockmax(old: Dict[str, dict], new: Dict[str, dict],
                     threshold_pct: float) -> Tuple[List[dict], List[str]]:
    """Gate the block-max A/B WITHIN the new round — both arms of a
    blockmax run land in one file, keyed `X` / `X_bmx` at the same
    (docs, devices) config:

    - any top-k page-digest divergence between the arms fails: the
      pruned page must be byte-identical to the unpruned page (totals
      are exempt by design — the pruned arm reports lower bounds with
      relation "gte");
    - at ≤ BLOCKMAX_P50_MAX_DOCS docs, the pruned arm's warm p50 may
      not exceed the unpruned arm's by more than BLOCKMAX_P50_PCT;
    - each arm's cross-round drift rides the generic warm gate above
      (the `_bmx` suffix keeps the arms from mis-pairing there).

    The old file's pairs are context, not gates: a historical
    divergence was that round's failure, not this one's."""
    del threshold_pct, old
    rows, failures = [], []
    for base, off, on in _blockmax_pairs(new):
        row = {"config": base, "docs": on.get("docs"),
               "pruned_fraction": on.get("pruned_fraction")}
        if off is None:
            row["status"] = "pruned-only"
            rows.append(row)
            continue
        status = "ok"
        od, nd = off.get("page_digest"), on.get("page_digest")
        row["digest_match"] = (od == nd) if od and nd else None
        if od and nd and od != nd:
            status = "PAGE-DIVERGENCE"
            failures.append(
                f"{base}: pruned arm page digest {nd} != unpruned "
                f"{od} — block-max pruning changed a top-k page")
        o50, n50 = warm_p50(off), warm_p50(on)
        row["unpruned_warm_p50_ms"] = o50
        row["pruned_warm_p50_ms"] = n50
        docs = on.get("docs")
        if o50 and n50:
            d50 = 100.0 * (n50 - o50) / o50
            row["p50_delta_pct"] = round(d50, 1)
            if status == "ok" and isinstance(docs, int) \
                    and docs <= BLOCKMAX_P50_MAX_DOCS \
                    and d50 > BLOCKMAX_P50_PCT:
                status = "ENABLED-OVERHEAD"
                failures.append(
                    f"{base}: pruned arm warm p50 {o50}ms -> {n50}ms "
                    f"(+{d50:.1f}% > {BLOCKMAX_P50_PCT:g}% at "
                    f"{docs} docs ≤ {BLOCKMAX_P50_MAX_DOCS} — the "
                    f"gate must be ~free below the trigger scale)")
        row["status"] = status
        rows.append(row)
    return rows, failures


def render_blockmax(rows: List[dict]) -> str:
    headers = ["config", "docs", "pruned_fraction", "digest_match",
               "unpruned_warm_p50_ms", "pruned_warm_p50_ms",
               "p50_delta_pct", "status"]
    table = [headers] + [[str(r.get(h, "-")) for h in headers]
                         for r in rows]
    widths = [max(len(row[i]) for row in table)
              for i in range(len(headers))]
    return "\n".join(
        "  ".join(cell.ljust(w) for cell, w in zip(row, widths)).rstrip()
        for row in table)


def render_kernels(rows: List[dict]) -> str:
    headers = ["config", "old_calls", "new_calls", "old_p50_ms",
               "new_p50_ms", "p50_delta_pct", "bound", "status"]
    table = [headers] + [[str(r.get(h, "-")) for h in headers]
                         for r in rows]
    widths = [max(len(row[i]) for row in table)
              for i in range(len(headers))]
    return "\n".join(
        "  ".join(cell.ljust(w) for cell, w in zip(row, widths)).rstrip()
        for row in table)


def render_maxsim(rows: List[dict]) -> str:
    headers = ["config", "old_recall_at_10", "new_recall_at_10",
               "recall_drop", "recall_vs_exact", "status"]
    table = [headers] + [[str(r.get(h, "-")) for h in headers]
                         for r in rows]
    widths = [max(len(row[i]) for row in table)
              for i in range(len(headers))]
    return "\n".join(
        "  ".join(cell.ljust(w) for cell, w in zip(row, widths)).rstrip()
        for row in table)


def render_page(rows: List[dict]) -> str:
    headers = ["config", "result_page", "round_trips_per_wave",
               "old_d2h_bytes_per_wave", "new_d2h_bytes_per_wave",
               "bytes_ratio", "status"]
    table = [headers] + [[str(r.get(h, "-")) for h in headers]
                         for r in rows]
    widths = [max(len(row[i]) for row in table)
              for i in range(len(headers))]
    return "\n".join(
        "  ".join(cell.ljust(w) for cell, w in zip(row, widths)).rstrip()
        for row in table)


def render_insights(rows: List[dict]) -> str:
    headers = ["config", "shape", "old_count", "new_count",
               "old_p99_ms", "new_p99_ms", "p99_delta_pct", "status"]
    table = [headers] + [[str(r.get(h, "-")) for h in headers]
                         for r in rows]
    widths = [max(len(row[i]) for row in table)
              for i in range(len(headers))]
    return "\n".join(
        "  ".join(cell.ljust(w) for cell, w in zip(row, widths)).rstrip()
        for row in table)


def render_scaling(rows: List[dict]) -> str:
    headers = ["config", "devices", "old_efficiency", "new_efficiency",
               "efficiency_delta_pct", "old_skew_p50_ms",
               "new_skew_p50_ms", "status"]
    table = [headers] + [[str(r.get(h, "-")) for h in headers]
                         for r in rows]
    widths = [max(len(row[i]) for row in table)
              for i in range(len(headers))]
    return "\n".join(
        "  ".join(cell.ljust(w) for cell, w in zip(row, widths)).rstrip()
        for row in table)


def render_interference(rows: List[dict]) -> str:
    headers = ["config", "ingest_rate", "old_p99_ms", "new_p99_ms",
               "p99_delta_pct", "old_ingest_dps", "new_ingest_dps",
               "ingest_delta_pct", "status"]
    table = [headers] + [[str(r.get(h, "-")) for h in headers]
                         for r in rows]
    widths = [max(len(row[i]) for row in table)
              for i in range(len(headers))]
    return "\n".join(
        "  ".join(cell.ljust(w) for cell, w in zip(row, widths)).rstrip()
        for row in table)


def render_overload(rows: List[dict]) -> str:
    headers = ["config", "offered_rate", "old_goodput", "new_goodput",
               "goodput_delta_pct", "past_knee", "old_admitted_p99_ms",
               "new_admitted_p99_ms", "status"]
    table = [headers] + [[str(r.get(h, "-")) for h in headers]
                         for r in rows]
    widths = [max(len(row[i]) for row in table)
              for i in range(len(headers))]
    return "\n".join(
        "  ".join(cell.ljust(w) for cell, w in zip(row, widths)).rstrip()
        for row in table)


def render(rows: List[dict]) -> str:
    headers = ["config", "old_warm_p50_ms", "new_warm_p50_ms",
               "delta_pct", "old_warm_p99_ms", "new_warm_p99_ms",
               "p99_delta_pct", "qps_delta_pct", "status"]
    table = [headers] + [[str(r.get(h, "-")) for h in headers]
                         for r in rows]
    widths = [max(len(row[i]) for row in table)
              for i in range(len(headers))]
    return "\n".join(
        "  ".join(cell.ljust(w) for cell, w in zip(row, widths)).rstrip()
        for row in table)


def main(argv: List[str]) -> int:
    threshold = 10.0
    args: List[str] = []
    rest = list(argv[1:])
    while rest:
        a = rest.pop(0)
        if a.startswith("--threshold"):
            threshold = float(a.split("=", 1)[1]) if "=" in a \
                else float(rest.pop(0))
        else:
            args.append(a)
    if len(args) != 2:
        print("usage: bench_compare.py [--threshold PCT] OLD.json NEW.json")
        return 2
    old, new = load_records(args[0]), load_records(args[1])
    if not old or not new:
        print(f"no parsable bench records in "
              f"{args[0] if not old else args[1]}")
        return 2
    rows, failures = compare(old, new, threshold)
    print(render(rows))
    ov_rows, ov_failures = compare_overload(old, new, threshold)
    if ov_rows:
        print("\noverload curve (goodput vs offered load):")
        print(render_overload(ov_rows))
        failures += ov_failures
    if_rows, if_failures = compare_interference(old, new, threshold)
    if if_rows:
        print("\ninterference sweep (search p99 / ingest throughput "
              "at equal ingest rate):")
        print(render_interference(if_rows))
        failures += if_failures
    sc_rows, sc_failures = compare_scaling(old, new, threshold)
    if sc_rows:
        print("\nmulti-chip scaling (per-chip efficiency / straggler "
              "skew at equal device count):")
        print(render_scaling(sc_rows))
        failures += sc_failures
    pg_rows, pg_failures = compare_page(old, new, threshold)
    if pg_rows:
        print("\nresult page (device round trips per wave / "
              "page-vs-legacy d2h bytes):")
        print(render_page(pg_rows))
        failures += pg_failures
    in_rows, in_failures = compare_insights(old, new, threshold)
    if in_rows:
        print("\nquery insights (per-shape warm p99 at equal shape "
              "key):")
        print(render_insights(in_rows))
        failures += in_failures
    mx_rows, mx_failures = compare_maxsim(old, new, threshold)
    if mx_rows:
        print("\nlate-interaction maxsim (recall@10 at equal config "
              "key / PQ recall-vs-exact floor):")
        print(render_maxsim(mx_rows))
        failures += mx_failures
    kr_rows, kr_failures = compare_kernels(old, new, threshold)
    if kr_rows:
        print("\nkernel profiler (per-family device p50 at equal "
              "bench+family key):")
        print(render_kernels(kr_rows))
        failures += kr_failures
    bm_rows, bm_failures = compare_blockmax(old, new, threshold)
    if bm_rows:
        print("\nblock-max A/B (pruned vs unpruned arm at equal "
              "config key — page-digest identity / ≤1M warm-p50):")
        print(render_blockmax(bm_rows))
        failures += bm_failures
    if failures:
        print(f"\nFAIL: {len(failures)} regression(s) "
              f"(warm p50/p99 beyond {threshold:g}% / overload "
              f"goodput-collapse / admitted-p99 breach):")
        for f in failures:
            print(f"  {f}")
        return 1
    print(f"\nOK: no warm-p50/p99 regression beyond {threshold:g}%, "
          f"no overload collapse")
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv))
