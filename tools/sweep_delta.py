"""Sweep delta: re-run the crash-fixed YAML suites + the search-pipeline
suite and FAIL on any 5xx.

The full reference YAML sweep (tools/yaml_sweep.py) needs the reference
checkout at /root/reference; this tool pins the three suites whose
round-5 sweep failures were 500-class crashes (VERDICT.md §weak-4):

  search.aggregation/70_adjacency_matrix.yml  — TypeError: '<' not
      supported (non-string agg/filter keys from YAML's unquoted numeric
      mapping keys)
  search/110_field_collapsing.yml             — TypeError: InternalEngine
      .index() got an unexpected keyword argument 'external_version'
      (the suite's setup indexes with ?version_type=external)
  search/250_distance_feature.yml             — TypeError: float() on a
      geo origin (distance_feature on geo_point)

Each suite below reproduces the reference suite's do-steps in-process
(the checkout is not required), plus a new search-pipeline suite covering
the subsystem end-to-end. Any response >= 500 fails the run. Wired into
tier-1 as tests/test_sweep_delta.py (non-slow). When /root/reference IS
present, the real YAML files for the three suites are executed as well
(5xx check only — match assertions stay tools/yaml_sweep.py's job).
"""

from __future__ import annotations

import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)
    sys.path.insert(0, os.path.join(REPO, "tests"))


def _fresh_node():
    from opensearch_tpu.node import Node
    return Node()


def _do(node, results, method, path, body=None, **params):
    """One do-step through the in-process REST dispatch (dict bodies pass
    through RestRequest exactly like the YAML runner hands them over)."""
    from opensearch_tpu.rest.controller import RestRequest
    raw = None
    if isinstance(body, (str, bytes)):
        raw = body.encode() if isinstance(body, str) else body
        body = None
    req = RestRequest(method=method, path=path,
                      params={k: str(v) for k, v in params.items()},
                      body=body, raw_body=raw)
    resp = node.controller.dispatch(req)
    results.append((f"{method} {path}", resp.status, resp.body))
    return resp


def _bulk_lines(*pairs):
    return "\n".join(json.dumps(line) for line in pairs) + "\n"


# --------------------------------------------------------------- suites

def suite_adjacency_matrix():
    """search.aggregation/70_adjacency_matrix.yml: filter intersections,
    including the unquoted-numeric-filter-name shape YAML produces."""
    node = _fresh_node()
    results = []
    _do(node, results, "PUT", "/test",
        {"settings": {"number_of_shards": 1},
         "mappings": {"properties": {"num": {"type": "integer"}}}})
    _do(node, results, "POST", "/_bulk", _bulk_lines(
        {"index": {"_index": "test", "_id": "1"}}, {"num": [1, 2]},
        {"index": {"_index": "test", "_id": "2"}}, {"num": [2, 3]},
        {"index": {"_index": "test", "_id": "3"}}, {"num": [3, 4]}),
        refresh="true")
    _do(node, results, "POST", "/test/_search",
        {"size": 0, "aggs": {"conns": {"adjacency_matrix": {"filters": {
            "f1": {"term": {"num": 1}},
            "f2": {"term": {"num": 2}},
            "f4": {"term": {"num": 4}}}}}}},
        rest_total_hits_as_int="true")
    # the crash shape: pyyaml parses unquoted numeric mapping keys as
    # ints, which reached the agg path as non-string dict keys
    _do(node, results, "POST", "/test/_search",
        {"size": 0, "aggs": {"conns": {"adjacency_matrix": {"filters": {
            1: {"term": {"num": 1}},
            2: {"term": {"num": 2}},
            "f4": {"term": {"num": 4}}}}}}})
    # "Terms lookup" section: the lookup shape is unsupported — must be a
    # 4xx parsing error, never a 500
    _do(node, results, "POST", "/test/_search",
        {"size": 0, "aggs": {"conns": {"adjacency_matrix": {"filters": {
            "lookup": {"terms": {"num": {"index": "lkp", "id": "1",
                                         "path": "nums"}}}}}}}})
    return results


def suite_field_collapsing():
    """search/110_field_collapsing.yml: the setup indexes every doc with
    an EXTERNAL version (?version_type=external) — the round-5 crash —
    then collapses on numeric_group."""
    node = _fresh_node()
    results = []
    _do(node, results, "PUT", "/test",
        {"mappings": {"properties": {"numeric_group": {"type":
                                                       "integer"}}}})
    docs = [("1", {"numeric_group": 1, "sort": 10}, 11),
            ("2", {"numeric_group": 1, "sort": 6}, 22),
            ("3", {"numeric_group": 1, "sort": 24}, 33),
            ("4", {"numeric_group": 25, "sort": 10}, 44),
            ("5", {"numeric_group": 25, "sort": 5}, 55),
            ("6", {"numeric_group": 25, "sort": 8}, 66)]
    for doc_id, body, version in docs:
        _do(node, results, "POST", f"/test/_doc/{doc_id}", body,
            version=version, version_type="external")
    _do(node, results, "POST", "/test/_refresh")
    _do(node, results, "POST", "/test/_search",
        {"collapse": {"field": "numeric_group"},
         "sort": [{"sort": "desc"}], "version": True})
    _do(node, results, "POST", "/test/_search",
        {"collapse": {"field": "numeric_group"},
         "sort": [{"sort": "desc"}], "from": 2})
    return results


def suite_distance_feature():
    """search/250_distance_feature.yml: the geo_point section (TypeError:
    float() on the [lon, lat] origin) plus the numeric/date sections."""
    node = _fresh_node()
    results = []
    _do(node, results, "PUT", "/index1",
        {"mappings": {"properties": {
            "location": {"type": "geo_point"},
            "date": {"type": "date"},
            "population": {"type": "integer"}}}})
    _do(node, results, "POST", "/_bulk", _bulk_lines(
        {"index": {"_index": "index1", "_id": "1"}},
        {"location": [-71.34, 41.12], "date": "2018-02-01",
         "population": 1000},
        {"index": {"_index": "index1", "_id": "2"}},
        {"location": [-71.30, 41.15], "date": "2018-03-01",
         "population": 3000},
        {"index": {"_index": "index1", "_id": "3"}},
        {"location": [-71.35, 41.12], "date": "2018-02-15",
         "population": 2000}), refresh="true")
    for origin in ([-71.35, 41.12], "41.12,-71.35",
                   {"lat": 41.12, "lon": -71.35}):
        _do(node, results, "POST", "/index1/_search",
            {"query": {"distance_feature": {
                "field": "location", "pivot": "1km", "origin": origin}}})
    _do(node, results, "POST", "/index1/_search",
        {"query": {"distance_feature": {
            "field": "population", "pivot": 500, "origin": 1000}}})
    _do(node, results, "POST", "/index1/_search",
        {"query": {"distance_feature": {
            "field": "date", "pivot": "7d", "origin": "2018-02-15"}}})
    return results


def suite_search_pipeline():
    """New subsystem suite: pipeline CRUD + processors + hybrid query
    through ?search_pipeline= and the index default setting."""
    node = _fresh_node()
    results = []
    _do(node, results, "PUT", "/sp",
        {"settings": {"number_of_shards": 2},
         "mappings": {"properties": {
             "title": {"type": "text"},
             "color": {"type": "keyword"},
             "vec": {"type": "knn_vector", "dimension": 4,
                     "method": {"space_type": "l2"}}}}})
    _do(node, results, "POST", "/_bulk", _bulk_lines(
        {"index": {"_index": "sp", "_id": "1"}},
        {"title": "red fox", "color": "red", "vec": [1, 0, 0, 0]},
        {"index": {"_index": "sp", "_id": "2"}},
        {"title": "brown dog", "color": "brown", "vec": [0, 1, 0, 0]},
        {"index": {"_index": "sp", "_id": "3"}},
        {"title": "red dog", "color": "red", "vec": [0.9, 0.2, 0, 0]},
        {"index": {"_index": "sp", "_id": "4"}},
        {"title": "blue cat", "color": "blue", "vec": [0, 0, 1, 0]}),
        refresh="true")
    _do(node, results, "PUT", "/_search/pipeline/hybrid-pipe", {
        "request_processors": [
            {"filter_query": {"query": {"terms": {
                "color": ["red", "brown", "blue"]}}}},
            {"oversample": {"sample_factor": 2.0}}],
        "phase_results_processors": [{"normalization-processor": {
            "normalization": {"technique": "min_max"},
            "combination": {"technique": "arithmetic_mean",
                            "parameters": {"weights": [0.4, 0.6]}}}}],
        "response_processors": [
            {"rename_field": {"field": "color",
                              "target_field": "colour"}},
            {"truncate_hits": {}}]})
    _do(node, results, "GET", "/_search/pipeline")
    _do(node, results, "GET", "/_search/pipeline/hybrid-pipe")
    hybrid_body = {"query": {"hybrid": {"queries": [
        {"match": {"title": "red"}},
        {"knn": {"vec": {"vector": [1, 0, 0, 0], "k": 3}}}]}},
        "size": 2}
    _do(node, results, "POST", "/sp/_search", hybrid_body,
        search_pipeline="hybrid-pipe")
    _do(node, results, "POST", "/sp/_search", hybrid_body)
    _do(node, results, "PUT", "/sp/_settings",
        {"index": {"search": {"default_pipeline": "hybrid-pipe"}}})
    _do(node, results, "POST", "/sp/_search", hybrid_body)
    # l2 + geometric variant, and an empty sub-query edge case
    _do(node, results, "PUT", "/_search/pipeline/l2-pipe", {
        "phase_results_processors": [{"normalization-processor": {
            "normalization": {"technique": "l2"},
            "combination": {"technique": "geometric_mean"}}}]})
    _do(node, results, "POST", "/sp/_search",
        {"query": {"hybrid": {"queries": [
            {"match": {"title": "nosuchterm"}},
            {"knn": {"vec": {"vector": [0, 0, 1, 0], "k": 2}}}]}}},
        search_pipeline="l2-pipe")
    # error contract: bad shapes must be 4xx, never 5xx
    _do(node, results, "POST", "/sp/_search",
        {"query": {"bool": {"must": [{"hybrid": {"queries": [
            {"match_all": {}}]}}]}}})
    _do(node, results, "POST", "/sp/_search",
        {"query": {"hybrid": {"queries": []}}})
    _do(node, results, "POST", "/sp/_search", hybrid_body,
        search_pipeline="missing-pipe")
    _do(node, results, "DELETE", "/_search/pipeline/l2-pipe")
    _do(node, results, "GET", "/_search/pipeline/l2-pipe")
    return results


SUITES = {
    "search.aggregation/70_adjacency_matrix.yml": suite_adjacency_matrix,
    "search/110_field_collapsing.yml": suite_field_collapsing,
    "search/250_distance_feature.yml": suite_distance_feature,
    "search.pipeline/10_pipeline_crud_and_hybrid.yml":
        suite_search_pipeline,
}


def run_reference_suites():
    """When the reference checkout is present, additionally run the real
    YAML files of the three fixed suites, checking 5xx only."""
    try:
        import yaml_rest_runner as yr
    except ImportError:
        return []
    if not yr.available():
        return []
    from opensearch_tpu.node import Node
    failures = []
    for suite in ("search.aggregation/70_adjacency_matrix.yml",
                  "search/110_field_collapsing.yml",
                  "search/250_distance_feature.yml"):
        path = os.path.join(yr.TEST_DIR, suite)
        if not os.path.exists(path):
            continue
        setup, _teardown, tests = yr.load_suite(path)
        for name, steps in tests:
            node = Node()
            try:
                yr.run_case(node, setup, steps)
            except yr.SkipTest:
                continue
            except Exception as e:
                msg = str(e)
                if "-> 5" in msg or "500" in msg.split(":")[0]:
                    failures.append(f"{suite}::{name}: {msg[:160]}")
    return failures


def run_all():
    """Returns (report dict, failures list). A failure is any response
    with status >= 500."""
    report = {}
    failures = []
    for suite, fn in SUITES.items():
        results = fn()
        statuses = [status for _, status, _ in results]
        report[suite] = statuses
        for step, status, body in results:
            if status >= 500:
                failures.append(
                    f"{suite} [{step}] -> {status}: "
                    f"{json.dumps(body, default=str)[:200]}")
    failures.extend(run_reference_suites())
    return report, failures


def main():
    import jax
    jax.config.update("jax_platforms", "cpu")
    report, failures = run_all()
    for suite, statuses in report.items():
        print(f"{'FAIL' if any(s >= 500 for s in statuses) else 'OK  '} "
              f"{suite} statuses={statuses}")
    if failures:
        print(f"\n{len(failures)} 5xx failure(s):")
        for f in failures:
            print(" ", f)
        sys.exit(1)
    print("\nno 5xx — sweep delta clean")


if __name__ == "__main__":
    main()
