#!/usr/bin/env python
"""Open-loop concurrent-clients harness: Poisson arrivals, coordinated-
omission-safe latency.

The closed-loop bench (bench.py's batch/latency passes) measures "how
fast can ONE caller pump requests" — it cannot see contention, and its
latency numbers suffer coordinated omission: a stalled server delays the
*sending* of the next request, so the stall's queueing damage never
appears in the recorded distribution. This harness is the open-loop
counterpart (ROADMAP item 2's acceptance instrument):

- arrivals follow a seeded Poisson process at `arrival_rate`/s — the
  request schedule is fixed BEFORE the run and never slows down because
  the server did;
- `clients` worker threads drain the schedule; a request whose intended
  arrival has passed starts immediately (late), and its latency is
  measured FROM THE INTENDED ARRIVAL TIME — the wrk2 correction — so a
  server stall charges every request it delayed, not just the one it
  served slowly;
- `queue_wait` (service start − intended arrival) is reported
  separately: it is the number the item-2 wave scheduler's admission
  control will be judged by.

Pure stdlib; importable by bench.py (`--clients/--arrival-rate`) and by
tests/test_openloop.py, which pins the coordinated-omission property
against a synthetic server with an injected stall (common/faults.py).
"""

from __future__ import annotations

import random
import threading
import time
from typing import Callable, List, Optional, Sequence


def poisson_schedule(n: int, rate: float, seed: int = 0) -> List[float]:
    """n intended arrival offsets (seconds from start) of a Poisson
    process at `rate` arrivals/s — seeded, so a run is reproducible."""
    if rate <= 0:
        raise ValueError(f"arrival rate must be > 0, got {rate}")
    rng = random.Random(seed)
    t, out = 0.0, []
    for _ in range(n):
        t += rng.expovariate(rate)
        out.append(t)
    return out


def percentile(sorted_vals: Sequence[float], p: float) -> float:
    if not sorted_vals:
        return 0.0
    i = min(len(sorted_vals) - 1, int(len(sorted_vals) * p))
    return sorted_vals[i]


def run_open_loop(serve: Callable, items: Sequence, clients: int = 8,
                  arrival_rate: float = 50.0, seed: int = 0,
                  schedule: Optional[Sequence[float]] = None) -> dict:
    """Drive `serve(item)` once per item from `clients` worker threads
    on an open-loop schedule. Returns the latency/queue-wait digest plus
    the raw per-request arrays (callers strip those before JSON).

    Latency[i] = completion − intended arrival (coordinated-omission
    safe); queue_wait[i] = max(service start − intended arrival, 0);
    service[i] = completion − service start (the closed-loop-style
    number, reported so the two can be compared — the CO test asserts
    they diverge under a stall).

    Goodput (the overload-sweep contract, ISSUE 11): `serve` may return
    an HTTP status int (or an object with `.status`) and each request
    classifies as ok (< 400), **rejected** (429 — an admission shed) or
    error (any other 4xx/5xx; raising still counts under `errors`). The
    digest splits the percentiles: `admitted_*` are service-time
    percentiles over OK requests only (the "admitted p99 stays bounded"
    number — open-loop latency from intended arrival grows without
    bound past saturation by construction, so it cannot be the SLO
    gate), `rejected_p99_ms` is the service-time p99 of sheds (the
    "rejected in <5 ms" check), and `goodput_qps` counts only OK
    completions. A None return keeps the old contract: everything that
    didn't raise is ok."""
    n = len(items)
    sched = list(schedule) if schedule is not None \
        else poisson_schedule(n, arrival_rate, seed)
    if len(sched) != n:
        raise ValueError(f"schedule has {len(sched)} entries for {n} items")
    lat = [0.0] * n
    qwait = [0.0] * n
    service = [0.0] * n
    status = [0] * n            # 0 = ok-by-default (None return)
    errors = [0]
    next_i = [0]
    lock = threading.Lock()
    t0 = time.monotonic()

    def worker():
        while True:
            with lock:
                i = next_i[0]
                next_i[0] += 1
            if i >= n:
                return
            intended = t0 + sched[i]
            now = time.monotonic()
            if now < intended:
                time.sleep(intended - now)
            t_start = time.monotonic()
            try:
                out = serve(items[i])
                st = getattr(out, "status", out)
                if isinstance(st, int):
                    status[i] = st
            except Exception:
                status[i] = -1
                with lock:
                    errors[0] += 1
            t_end = time.monotonic()
            lat[i] = (t_end - intended) * 1000.0
            qwait[i] = max((t_start - intended) * 1000.0, 0.0)
            service[i] = (t_end - t_start) * 1000.0

    threads = [threading.Thread(target=worker, daemon=True,
                                name=f"openloop-client-{c}")
               for c in range(max(int(clients), 1))]
    for th in threads:
        th.start()
    for th in threads:
        th.join()
    wall_s = time.monotonic() - t0
    ok_i = [i for i in range(n) if 0 <= status[i] < 400]
    rej_i = [i for i in range(n) if status[i] == 429]
    err_i = [i for i in range(n)
             if status[i] >= 400 and status[i] != 429]
    s_lat = sorted(lat)
    s_srv = sorted(service)
    s_ok_srv = sorted(service[i] for i in ok_i)
    s_rej_srv = sorted(service[i] for i in rej_i)
    return {
        "clients": max(int(clients), 1),
        "arrival_rate": arrival_rate,
        "n_requests": n,
        "duration_s": round(wall_s, 3),
        "qps": round(n / wall_s, 2) if wall_s > 0 else 0.0,
        "p50_ms": round(percentile(s_lat, 0.50), 2),
        "p99_ms": round(percentile(s_lat, 0.99), 2),
        "p999_ms": round(percentile(s_lat, 0.999), 2),
        "max_ms": round(s_lat[-1], 2) if s_lat else 0.0,
        "mean_queue_wait_ms": round(sum(qwait) / max(n, 1), 3),
        "max_queue_wait_ms": round(max(qwait), 2) if qwait else 0.0,
        "service_p50_ms": round(percentile(s_srv, 0.50), 2),
        "service_p99_ms": round(percentile(s_srv, 0.99), 2),
        "errors": errors[0],
        # goodput split (admission-aware callers; all-ok otherwise)
        "ok": len(ok_i),
        "rejected": len(rej_i),
        "failed": len(err_i),
        "goodput_qps": round(len(ok_i) / wall_s, 2) if wall_s > 0
        else 0.0,
        "admitted_p50_ms": round(percentile(s_ok_srv, 0.50), 2),
        "admitted_p99_ms": round(percentile(s_ok_srv, 0.99), 2),
        "rejected_p50_ms": round(percentile(s_rej_srv, 0.50), 2),
        "rejected_p99_ms": round(percentile(s_rej_srv, 0.99), 2),
        # raw per-request arrays for downstream analysis; strip before
        # serializing a bench record
        "latencies_ms": lat,
        "queue_waits_ms": qwait,
        "service_ms": service,
        "statuses": status,
    }
