#!/usr/bin/env python
"""Render the multi-chip scaling-efficiency record (SCALING_MC_r*.json,
bench.py --devices — ISSUE 14).

One row per device count D: serving QPS on the real segment-sharded
SPMD path, per-chip scaling efficiency QPS(D)/(D·QPS(1)), straggler
skew (max−median per-chip wall), analytic collective bytes/query over
the ICI, and the live scanned-bytes counter (the block-max trigger
metric — SCALING.md's offline column, live). A per-device section
breaks each point down by chip: partial wall, straggler hits, h2d
bytes.

    python tools/scaling_report.py SCALING_MC_r01.json
    python tools/scaling_report.py --assert-efficiency 0.5 SCALING_MC_r01.json

--assert-efficiency F: exit 1 unless every multi-chip point (D > 1)
holds per-chip efficiency >= F — the harness's own floor check, next
to tools/bench_compare.py's cross-round 15% regression gate.
"""

from __future__ import annotations

import json
import os
import sys
from typing import List

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from trace_report import _render  # noqa: E402  (shared table renderer)


def load_records(path: str) -> List[dict]:
    """One JSON object per line (or one array) → scaling point dicts,
    sorted by device count; error points kept (reported, never
    silently dropped)."""
    text = (sys.stdin.read() if path == "-" else open(path).read()).strip()
    if not text:
        return []
    records: List[dict] = []
    if text[0] == "[":
        records = [r for r in json.loads(text) if isinstance(r, dict)]
    else:
        for line in text.splitlines():
            line = line.strip()
            if not line:
                continue
            try:
                obj = json.loads(line)
            except json.JSONDecodeError:
                continue
            if isinstance(obj, dict):
                records.append(obj)
    records = [r for r in records if "devices" in r]
    records.sort(key=lambda r: r["devices"])
    return records


def report_rows(records: List[dict]) -> List[dict]:
    rows = []
    for rec in records:
        if "error" in rec:
            rows.append({"devices": rec["devices"],
                         "mode": rec.get("mode", "-"),
                         "qps": "ERROR",
                         "efficiency": "-", "skew_p50_ms": "-",
                         "ici_bytes_q": "-", "scan_bytes_q": "-",
                         "eff_bytes_q": "-", "pruned_frac": "-"})
            continue
        rows.append({
            "devices": rec["devices"],
            "mode": rec.get("mode", "-"),
            "qps": f"{rec.get('value', 0):g}",
            "efficiency": f"{rec['per_chip_efficiency']:g}"
            if rec.get("per_chip_efficiency") is not None else "-",
            "skew_p50_ms": f"{rec['straggler_skew_p50_ms']:g}"
            if rec.get("straggler_skew_p50_ms") is not None else "-",
            "ici_bytes_q": f"{rec.get('collective_ici_bytes_per_query', 0):g}",
            "scan_bytes_q":
                f"{rec['scanned_bytes_per_query_p50']:.0f}"
                if rec.get("scanned_bytes_per_query_p50") else "-",
            # block-max overlay (ISSUE 20): the effective (post-pruning)
            # per-query posting bytes and the pruned share — the pruned
            # arm's payoff next to the static trigger column; unpruned
            # rows show effective == static (the scan conservation law)
            "eff_bytes_q":
                f"{rec['effective_bytes_per_query_p50']:.0f}"
                if rec.get("effective_bytes_per_query_p50") else "-",
            "pruned_frac": f"{rec['pruned_fraction']:g}"
            if rec.get("pruned_fraction") is not None else "-",
        })
    return rows


def device_rows(records: List[dict]) -> List[dict]:
    """Per-chip breakdown across every point: who straggled, who moved
    the bytes."""
    rows = []
    for rec in records:
        per_dev = rec.get("per_device") or {}
        for dev, ent in sorted(per_dev.items(), key=lambda kv: int(kv[0])):
            q = max(ent.get("queries", 0), 1)
            rows.append({
                "D": rec["devices"],
                "device": dev,
                "queries": ent.get("queries", 0),
                "partial_ms_per_q":
                    f"{ent.get('partial_ms', 0.0) / q:.3f}",
                "straggler_hits": ent.get("straggler_hits", 0),
                "h2d_bytes": ent.get("h2d_bytes", 0),
            })
    return rows


def main(argv: List[str]) -> int:
    min_eff = None
    args: List[str] = []
    rest = list(argv[1:])
    while rest:
        a = rest.pop(0)
        if a.startswith("--assert-efficiency"):
            min_eff = float(a.split("=", 1)[1]) if "=" in a \
                else float(rest.pop(0))
        else:
            args.append(a)
    path = args[0] if args else "SCALING_MC_r01.json"
    records = load_records(path)
    if not records:
        print(f"no scaling points found in {path} "
              f"(run: python bench.py --devices 1,2,4,8)")
        return 1
    print(f"multi-chip scaling ({path}): QPS(D) on the real SPMD "
          f"serving path, efficiency = QPS(D)/(D*QPS(1))")
    print(_render(report_rows(records),
                  ["devices", "mode", "qps", "efficiency", "skew_p50_ms",
                   "ici_bytes_q", "scan_bytes_q", "eff_bytes_q",
                   "pruned_frac"]))
    dev = device_rows(records)
    if dev:
        print("\nper-chip breakdown (partial wall per query, "
              "straggler hits, upload bytes):")
        print(_render(dev, ["D", "device", "queries", "partial_ms_per_q",
                            "straggler_hits", "h2d_bytes"]))
    if min_eff is not None:
        bad = [r for r in records
               if "error" not in r and r["devices"] > 1
               and (r.get("per_chip_efficiency") or 0) < min_eff]
        errors = [r for r in records if "error" in r]
        if bad or errors:
            for r in bad:
                print(f"FAIL: D={r['devices']} efficiency "
                      f"{r.get('per_chip_efficiency')} < {min_eff:g}")
            for r in errors:
                print(f"FAIL: D={r['devices']} errored: "
                      f"{r['error'][:120]}")
            return 1
        print(f"OK: every multi-chip point >= {min_eff:g} per-chip "
              f"efficiency")
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv))
